"""Project-invariant lint: the conventions the PR history established,
encoded as AST rules over the whole package (catalog + rationale in
ANALYSIS.md; run via ``python -m librdkafka_tpu.analysis lint`` or
``scripts/check.sh``).

Rules (ids are stable; suppress a line with ``# lint: ok <rule>``):

  sleep-poll       client/ must wait on condvars, never sleep-poll
                   (test_0120's contract; SyncReply replaced the
                   rounds-2/3 sleep loops)
  conf-prop        every conf Prop is validated (int/float: range or
                   validator; aliases inherit the target's) and has a
                   CONFIGURATION.md row (the generated docs and the
                   table must not drift)
  trace-guard      trace hook sites (evt/complete/instant) sit behind
                   an ``if <trace>.enabled:`` attr check or a guard
                   var assigned from one — the <2% disabled-overhead
                   contract of PR 5
  bare-except      no ``except:`` — it eats KeyboardInterrupt/
                   SystemExit and hides real faults in thread loops
  chaos-random     chaos/ and fleet/ randomness comes only from a
                   seeded ``random.Random`` (schedule or traffic
                   plan) — module-level random breaks same-seed
                   replay (CHAOS.md, FLEET.md)
  thread-name      every thread is named so the conftest leak fixture
                   can claim it (engine/sockem/chaos-sched matching)
  manual-acquire   no manual ``.acquire()`` — a raise between acquire
                   and release leaks the lock forever; use ``with``
  lock-factory     lock sites in client/, ops/engine.py, ops/tpu.py,
                   mock/, chaos/ and fleet/ create primitives through
                   analysis.locks so lockdep can instrument them
  shared-state     classes in the same scoped layers that start
                   threads or create factory locks must declare their
                   cross-thread mutable attributes via
                   analysis.races (shared()/register_slots()/
                   shared_dict()/shared_list()/shared_counter()) so
                   the lockset detector can see them — or carry a
                   class-line pragma with a written justification

The linter is intentionally lexical where data-flow would be needed
for perfection (e.g. trace-guard accepts ``if t0:`` when ``t0`` was
assigned from ``trace.now() if trace.enabled else 0`` in the same
function) — the goal is catching drift in review, not soundness.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Optional

#: module aliases accepted as "the tracer" by trace-guard
_TRACE_NAMES = {"trace", "_trace", "_tr"}
_TRACE_HOOKS = {"evt", "complete", "instant"}
_LOCK_CTORS = {"Lock", "RLock", "Condition"}

#: paths (relative to the package root, / separators) under the
#: lock-factory rule — the layers lockdep instruments
_FACTORY_SCOPE = ("client/", "mock/", "chaos/", "fleet/", "ops/engine.py",
                  "ops/tpu.py")

#: layers whose randomness must come from a seeded Random (the
#: replay-from-seed contract: CHAOS.md for schedules, FLEET.md for
#: traffic plans and worker sampling)
_SEEDED_RANDOM_SCOPE = ("chaos/", "fleet/")

#: calls that count as a shared-state declaration (analysis/races.py)
_SHARED_DECLS = {"shared", "shared_dict", "shared_list",
                 "shared_counter", "register_slots"}

#: files whose job exempts them from specific rules
_RULE_EXEMPT = {
    "manual-acquire": ("analysis/lockdep.py",),
    "trace-guard": ("obs/trace.py",),
    "lock-factory": ("analysis/",),
    "shared-state": ("analysis/",),
}

_PRAGMA = re.compile(r"#\s*lint:\s*ok\s+([a-z-]+(?:\s*,\s*[a-z-]+)*)")


@dataclass
class Finding:
    file: str
    line: int
    rule: str
    msg: str

    def __str__(self):
        return f"{self.file}:{self.line}: [{self.rule}] {self.msg}"


def _pragmas(src: str) -> dict[int, set[str]]:
    out: dict[int, set[str]] = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = _PRAGMA.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",")}
    return out


def _exempt(rule: str, relpath: str) -> bool:
    return any(relpath.startswith(p) or relpath == p
               for p in _RULE_EXEMPT.get(rule, ()))


class _GuardAttrs(ast.NodeVisitor):
    """Prepass: attribute names that carry a trace-guard truth value —
    assigned from ``<trace>.now()``, from a guard-conditional IfExp, or
    under an ``if <x>.enabled:`` block (e.g. ``self.t_crc_ns``) — so
    ``if self.t_crc_ns:`` counts as a guard downstream."""

    def __init__(self):
        self.attrs: set[str] = set()
        self._guard_names: set[str] = set()
        self._depth = 0

    @staticmethod
    def _guardish(node, names) -> bool:
        if isinstance(node, ast.Attribute) and node.attr == "enabled":
            return True
        if isinstance(node, ast.Name) and node.id in names:
            return True
        return False

    def _is_now_call(self, node) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "now"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in _TRACE_NAMES)

    def visit_If(self, node):
        guarded = self._guardish(node.test, self._guard_names)
        if guarded:
            self._depth += 1
        for n in node.body:
            self.visit(n)
        if guarded:
            self._depth -= 1
        for n in node.orelse:
            self.visit(n)

    def visit_Assign(self, node):
        v = node.value
        carries = (self._depth > 0 and self._is_now_call(v)) or (
            isinstance(v, ast.IfExp)
            and self._guardish(v.test, self._guard_names)) or (
            isinstance(v, ast.Name) and v.id in self._guard_names)
        if carries:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._guard_names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    self.attrs.add(t.attr)
        self.generic_visit(node)


class _Visitor(ast.NodeVisitor):
    def __init__(self, relpath: str, guard_attrs: Optional[set] = None):
        self.relpath = relpath
        self.findings: list[Finding] = []
        self._loop_depth = 0
        # per-function names assigned from `X if <trace>.enabled else Y`
        self._guard_vars: list[set[str]] = [set()]
        self._guard_attrs = guard_attrs or set()
        self._if_guard_depth = 0

    def _add(self, node, rule: str, msg: str):
        self.findings.append(Finding(self.relpath, node.lineno, rule, msg))

    # ---------------------------------------------------- helpers --
    @staticmethod
    def _is_enabled_attr(node) -> bool:
        """``<name>.enabled`` where <name> is a trace/lockdep alias —
        or any ``X.enabled`` attribute (other modules use the same
        pattern; a stray .enabled guard is not worth a false
        positive)."""
        return isinstance(node, ast.Attribute) and node.attr == "enabled"

    def _test_is_guard(self, test) -> bool:
        """Accepts `X.enabled`, boolean ops containing it, and bare
        names assigned from an enabled-conditional in this function."""
        if self._is_enabled_attr(test):
            return True
        if isinstance(test, ast.Name) and test.id in self._guard_vars[-1]:
            return True
        if isinstance(test, ast.Attribute) and test.attr in self._guard_attrs:
            return True
        if isinstance(test, ast.BoolOp):
            return any(self._test_is_guard(v) for v in test.values)
        if isinstance(test, ast.UnaryOp):
            return self._test_is_guard(test.operand)
        return False

    # ------------------------------------------------- structure --
    def _visit_fn(self, node):
        self._guard_vars.append(set())
        self.generic_visit(node)
        self._guard_vars.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Assign(self, node):
        # collect guard vars: t0 = trace.now() if trace.enabled else 0
        v = node.value
        if isinstance(v, ast.IfExp) and self._test_is_guard(v.test):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._guard_vars[-1].add(t.id)
        self.generic_visit(node)

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_While = _visit_loop
    visit_For = _visit_loop

    def visit_If(self, node):
        guarded = self._test_is_guard(node.test)
        if guarded:
            self._if_guard_depth += 1
        for n in node.body:
            self.visit(n)
        if guarded:
            self._if_guard_depth -= 1
        for n in node.orelse:
            self.visit(n)

    def visit_ExceptHandler(self, node):
        if node.type is None and not _exempt("bare-except", self.relpath):
            self._add(node, "bare-except",
                      "bare `except:` — name the exceptions (a bare "
                      "clause eats SystemExit/KeyboardInterrupt)")
        self.generic_visit(node)

    # ----------------------------------------------------- calls --
    def visit_Call(self, node):
        f = node.func
        # sleep-poll: time.sleep inside a loop, client/ only
        if (self.relpath.startswith("client/") and self._loop_depth > 0
                and isinstance(f, ast.Attribute) and f.attr == "sleep"
                and isinstance(f.value, ast.Name) and f.value.id == "time"
                and not _exempt("sleep-poll", self.relpath)):
            self._add(node, "sleep-poll",
                      "time.sleep in a client/ loop — wait on a "
                      "Condition/SyncReply instead (test_0120)")
        # trace-guard: unguarded trace hook call
        if (isinstance(f, ast.Attribute) and f.attr in _TRACE_HOOKS
                and isinstance(f.value, ast.Name)
                and f.value.id in _TRACE_NAMES
                and self._if_guard_depth == 0
                and not _exempt("trace-guard", self.relpath)):
            self._add(node, "trace-guard",
                      f"trace hook {f.value.id}.{f.attr}() outside an "
                      f"`if {f.value.id}.enabled:` guard (PR 5 "
                      "overhead contract)")
        # chaos-random: module-level random in chaos/ or fleet/
        if (self.relpath.startswith(_SEEDED_RANDOM_SCOPE)
                and isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "random" and f.attr != "Random"
                and not _exempt("chaos-random", self.relpath)):
            self._add(node, "chaos-random",
                      f"random.{f.attr}() in {self.relpath.split('/')[0]}/ "
                      "— draw from the schedule's/plan's seeded Random "
                      "so replay_key replays (CHAOS.md, FLEET.md)")
        # thread-name: threading.Thread(...) without name=
        if (isinstance(f, ast.Attribute) and f.attr in ("Thread", "Timer")
                and isinstance(f.value, ast.Name)
                and f.value.id == "threading"
                and not any(k.arg == "name" for k in node.keywords)
                and not _exempt("thread-name", self.relpath)):
            self._add(node, "thread-name",
                      "unnamed thread — pass name=... so the conftest "
                      "leak fixture can claim it")
        # thread-name (subclass form): super().__init__ without name=
        # is covered by the same rule when the class derives Thread —
        # kept lexical: super().__init__(...) inside a class whose
        # bases mention Thread is checked in _check_thread_subclass
        # manual-acquire
        if (isinstance(f, ast.Attribute) and f.attr == "acquire"
                and not _exempt("manual-acquire", self.relpath)):
            self._add(node, "manual-acquire",
                      "manual .acquire() — an exception before "
                      "release() leaks the lock; use `with`")
        # lock-factory: direct primitive creation in scoped layers
        if (any(self.relpath.startswith(p) for p in _FACTORY_SCOPE)
                and isinstance(f, ast.Attribute) and f.attr in _LOCK_CTORS
                and isinstance(f.value, ast.Name)
                and f.value.id == "threading"
                and not _exempt("lock-factory", self.relpath)):
            self._add(node, "lock-factory",
                      f"threading.{f.attr}() in a lockdep-scoped layer "
                      "— create it via analysis.locks.new_"
                      f"{'cond' if f.attr == 'Condition' else f.attr.lower()}"
                      "(name) so the checker can instrument it")
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        # Thread subclasses must pass name= to super().__init__
        derives_thread = any(
            (isinstance(b, ast.Attribute) and b.attr == "Thread")
            or (isinstance(b, ast.Name) and b.id == "Thread")
            for b in node.bases)
        if derives_thread and not _exempt("thread-name", self.relpath):
            for n in ast.walk(node):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "__init__"
                        and isinstance(n.func.value, ast.Call)
                        and isinstance(n.func.value.func, ast.Name)
                        and n.func.value.func.id == "super"
                        and not any(k.arg == "name" for k in n.keywords)):
                    self._add(n, "thread-name",
                              "Thread subclass __init__ without "
                              "name= — the conftest leak fixture "
                              "cannot claim it")
        self.generic_visit(node)


# ------------------------------------------------- shared-state rule --
def _call_name(node) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _lint_shared_state(tree: ast.AST, relpath: str) -> list[Finding]:
    """Concurrent classes must declare their cross-thread mutable
    attributes to the lockset detector (analysis/races.py).  A class
    in the lockdep-scoped layers "is concurrent" when it starts a
    thread (threading.Thread/Timer call, or a Thread base) or creates
    a factory lock; it "declares" when its body calls shared()/
    shared_dict()/shared_list()/shared_counter(), or a module-level
    register_slots(ClassName, ...) names it.  Suppress with a
    ``# lint: ok shared-state`` pragma ON THE CLASS LINE plus a
    written justification — the pragma is the judged-exception path,
    exactly like the runtime detector's ``relaxed=True``."""
    if not any(relpath.startswith(p) for p in _FACTORY_SCOPE):
        return []
    # prepass: classes declared via register_slots(Cls, ...)
    slot_declared: set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and _call_name(node) == "register_slots"
                and node.args and isinstance(node.args[0], ast.Name)):
            slot_declared.add(node.args[0].id)
    out: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        derives_thread = any(
            (isinstance(b, ast.Attribute) and b.attr == "Thread")
            or (isinstance(b, ast.Name) and b.id == "Thread")
            for b in node.bases)
        starts_thread = derives_thread
        makes_lock = False
        declares = node.name in slot_declared
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            cn = _call_name(n)
            if cn in ("Thread", "Timer") and isinstance(
                    n.func, ast.Attribute) and isinstance(
                    n.func.value, ast.Name) and \
                    n.func.value.id == "threading":
                starts_thread = True
            elif cn in ("new_lock", "new_rlock", "new_cond"):
                makes_lock = True
            elif cn in _SHARED_DECLS:
                declares = True
        if (starts_thread or makes_lock) and not declares:
            what = ("starts threads" if starts_thread
                    else "creates factory locks")
            out.append(Finding(
                relpath, node.lineno, "shared-state",
                f"class {node.name} {what} but declares no shared "
                "state — declare cross-thread mutable attributes via "
                "analysis.races (shared()/register_slots()/shared_*()) "
                "so the lockset detector sees them, or pragma the "
                "class line with a written justification"))
    return out


# --------------------------------------------------- conf-prop rule --
def _lint_conf_props(tree: ast.AST, relpath: str,
                     doc_names: Optional[set] = None) -> list[Finding]:
    """conf.py's PROPERTIES table: int/float Props need a range or
    validator (aliases inherit the target's), non-hidden Props need a
    CONFIGURATION.md row.  ``doc_names=None`` skips the doc check
    (fixture mode)."""
    out: list[Finding] = []
    props = None
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and node.targets:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if (target is not None and isinstance(target, ast.Name)
                and target.id == "PROPERTIES"):
            props = node.value
            break
    if props is None:
        return out
    for c in ast.walk(props):
        if not (isinstance(c, ast.Call) and isinstance(c.func, ast.Name)
                and c.func.id in ("_p", "Prop")):
            continue
        if len(c.args) < 3 or not isinstance(c.args[0], ast.Constant):
            continue
        name = c.args[0].value
        ptype = c.args[2].value if isinstance(c.args[2], ast.Constant) \
            else None
        kw = {k.arg: k.value for k in c.keywords}
        is_alias = "alias" in kw
        hidden = (isinstance(kw.get("hidden"), ast.Constant)
                  and kw["hidden"].value)
        if (ptype in ("int", "float") and not is_alias
                and not any(k in kw for k in ("vmin", "vmax",
                                              "validator"))):
            out.append(Finding(
                relpath, c.lineno, "conf-prop",
                f"Prop {name!r}: {ptype} without vmin/vmax or "
                "validator — a bad value must fail at set() time"))
        if doc_names is not None and not hidden and name not in doc_names:
            out.append(Finding(
                relpath, c.lineno, "conf-prop",
                f"Prop {name!r} has no CONFIGURATION.md row — "
                "regenerate: python -m librdkafka_tpu.client.conf"))
    return out


def _doc_names(root: str) -> Optional[set]:
    md = os.path.join(root, "..", "CONFIGURATION.md")
    if not os.path.exists(md):
        return None
    names = set()
    with open(md) as f:
        for line in f:
            if " | " in line and not line.startswith(("Property", "---")):
                names.add(line.split(" | ")[0].strip().strip("`"))
    return names


# ------------------------------------------------------ entry points --
def lint_source(src: str, relpath: str,
                doc_names: Optional[set] = None) -> list[Finding]:
    """Lint one file's source; ``relpath`` is package-root-relative
    with / separators (it scopes the path-dependent rules)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 0, "syntax", str(e))]
    pre = _GuardAttrs()
    pre.visit(tree)
    v = _Visitor(relpath, pre.attrs)
    v.visit(tree)
    findings = v.findings
    if not _exempt("shared-state", relpath):
        findings += _lint_shared_state(tree, relpath)
    if relpath == "client/conf.py":
        findings += _lint_conf_props(tree, relpath, doc_names)
    pragmas = _pragmas(src)
    return [f for f in findings
            if f.rule not in pragmas.get(f.line, ())]


def lint_package(root: Optional[str] = None) -> list[Finding]:
    """Lint every .py file under the package root (default: this
    package's parent, i.e. librdkafka_tpu/)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    doc_names = _doc_names(root)
    findings: list[Finding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                src = f.read()
            findings += lint_source(src, rel, doc_names)
    findings.sort(key=lambda f: (f.file, f.line))
    return findings


def main(argv: Optional[list] = None) -> int:
    import sys
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else None
    findings = lint_package(root)
    for f in findings:
        print(f)
    n = len(findings)
    print(f"lint: {n} finding(s)" if n else "lint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
