"""Runtime lock-order checker (the kernel-lockdep idea, in-process).

The reference client ships its locking discipline as build-time
tooling — helgrind/TSAN suppressions and the ``rd_kafka_*lock`` wrap
macros — because a deadlock that needs three threads and a slow broker
to line up will never show up in a unit test.  This module is that
tooling for the Python rebuild:

  * Locks are created through :mod:`.locks`'s ``new_lock/new_rlock/
    new_cond`` factory.  With the checker DISABLED (default) the
    factory returns plain ``threading`` primitives — the decision is
    made once at creation time, so the production hot path pays
    nothing at all (same near-zero-when-off contract as
    ``obs/trace.py``, just moved from per-event to per-object).
  * Enabled, the factory returns :class:`DepLock`/:class:`DepRLock`/
    :class:`DepCondition` wrappers.  Every acquisition is recorded
    against the per-thread stack of locks already held; each FIRST
    observation of "acquired B while holding A" stores one edge
    A->B in the global lock-order graph together with the acquiring
    thread's name and formatted stack (stacks are captured only when
    an edge is first seen, so steady-state tracking is dict lookups).
  * Locks are keyed by their *class name* (the string given to the
    factory), not by instance — two broker threads taking
    ``kafka.toppar`` then ``kafka.msg_cnt`` in opposite orders is an
    inversion even though the instances differ.  Same-name nesting of
    two DISTINCT instances records a self-edge and is reported (two
    threads + two instances + opposite order = deadlock); re-entrant
    acquisition of one :class:`DepRLock` instance is NOT an edge and
    is never flagged.
  * :func:`report` finds cycles in the order graph: a 2-cycle is an
    ``inconsistent_order`` pair (the classic AB/BA), anything longer a
    ``cycle`` — both reported with every participating edge's stack.
  * Blocking calls (socket select/connect, device launch readback,
    ``queue.get``-style waits) are marked at the call site with
    ``if lockdep.enabled: lockdep.note_blocking("what")``; holding ANY
    tracked lock there is a ``held_across_blocking`` violation with
    both the lock's acquisition stack and the blocking site's stack.
    Condition waits are exempt by construction — ``wait()`` releases
    the condvar lock through the wrapper, so the held-set is already
    correct when the thread parks.

The checker is refcounted like the tracer (N clients may enable it via
the ``analysis.lockdep`` conf knob; ``pytest --lockdep`` holds one
reference for the whole session).  State survives disable() so the
graph can be inspected after a run; :func:`reset` clears it.
"""
from __future__ import annotations

import threading
import traceback
from contextlib import contextmanager
from typing import Optional

from . import interleave as _itl

#: master switch — the locks factory consults this at CREATION time,
#: instrumented primitives consult it per acquisition (so a disable()
#: mid-run stops recording without swapping objects out)
enabled = False

#: stack frames kept per captured edge/violation stack
STACK_DEPTH = 16

_enable_count = 0


class _Edge:
    """One observed order "from -> to" with the stack that created it."""

    __slots__ = ("src", "dst", "thread", "stack", "held_stack", "count")

    def __init__(self, src: str, dst: str, thread: str, stack: str,
                 held_stack: Optional[str]):
        self.src = src
        self.dst = dst
        self.thread = thread
        self.stack = stack              # where dst was acquired
        self.held_stack = held_stack    # where src had been acquired
        self.count = 1

    def as_dict(self) -> dict:
        return {"from": self.src, "to": self.dst, "thread": self.thread,
                "count": self.count, "stack": self.stack,
                "held_stack": self.held_stack}


class _State:
    """The global order graph + violation lists (swappable for tests)."""

    def __init__(self):
        self.lock = threading.Lock()    # plain: guards the dicts below
        self.edges: dict[tuple[str, str], _Edge] = {}
        self.adj: dict[str, set[str]] = {}
        self.classes: set[str] = set()
        self.blocking: list[dict] = []
        self._blocking_seen: set[tuple[str, str]] = set()
        self.acquisitions = 0


_state = _State()
_local = threading.local()


def _held() -> list:
    """This thread's stack of currently-held instrumented locks —
    entries are [lock_obj, class_name, acquire_stack_str_or_None]."""
    h = getattr(_local, "held", None)
    if h is None:
        h = _local.held = []
    return h


def _capture() -> str:
    return "".join(traceback.format_stack(limit=STACK_DEPTH)[:-2])


def _note_acquire(obj, name: str) -> None:
    if not enabled:
        return
    held = _held()
    st = _state
    with st.lock:
        st.acquisitions += 1
        st.classes.add(name)
        new_edges = []
        for ent in held:
            src = ent[1]
            if src == name and ent[0] is obj:
                continue        # re-entrant same instance (DepRLock)
            key = (src, name)
            e = st.edges.get(key)
            if e is not None:
                e.count += 1
            else:
                new_edges.append(ent)
        if new_edges:
            stack = _capture()
            for ent in new_edges:
                key = (ent[1], name)
                st.edges[key] = _Edge(ent[1], name,
                                      threading.current_thread().name,
                                      stack, ent[2])
                st.adj.setdefault(ent[1], set()).add(name)
    # No per-acquire stack capture: locks are taken via ``with`` (the
    # lint forbids manual acquire()), so the holder's frame is still ON
    # the current stack whenever a nested acquire creates an edge or a
    # blocking marker fires — the single capture taken there shows both
    # acquisition sites.  This keeps steady-state tracking at dict
    # lookups (stacks are captured only for NEW edges/violations).
    held.append([obj, name, None])


def _note_release(obj) -> None:
    held = getattr(_local, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is obj:
            del held[i]
            return


def held_locks() -> list:
    """This thread's currently-held instrumented locks as
    ``[(lock_obj, class_name)]`` — the lockset source for the Eraser-
    style detector (analysis/races.py): each declared-variable access
    snapshots this stack and refines its candidate set with it."""
    held = getattr(_local, "held", None)
    if not held:
        return []
    return [(e[0], e[1]) for e in held]


def note_blocking(what: str) -> None:
    """Call-site marker for a blocking operation (socket select or
    connect, device readback, ``queue.get``).  Guard with
    ``if lockdep.enabled:`` — this function is the slow path."""
    if not enabled:
        return
    held = getattr(_local, "held", None)
    if not held:
        return
    st = _state
    with st.lock:
        for ent in held:
            key = (what, ent[1])
            if key in st._blocking_seen:
                continue
            st._blocking_seen.add(key)
            st.blocking.append({
                "call": what,
                "lock": ent[1],
                "thread": threading.current_thread().name,
                "stack": _capture(),
                "held_stack": ent[2],
            })


# ------------------------------------------------ instrumented types --
class DepLock:
    """Instrumented ``threading.Lock``."""

    def __init__(self, name: str):
        self.name = name
        self._lk = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _itl.active:
            # schedule-explorer yield point (analysis/interleave.py):
            # a preemption just before the acquire is how another
            # thread wins a race for this lock's critical section
            _itl.maybe_yield(f"lock:{self.name}")
        got = self._lk.acquire(blocking, timeout)
        if got:
            _note_acquire(self, self.name)
        return got

    def release(self) -> None:
        _note_release(self)
        self._lk.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<DepLock {self.name!r}>"


class DepRLock:
    """Instrumented ``threading.RLock``: only the OUTERMOST acquisition
    records an edge — re-entrancy is the type's contract, not an
    ordering fact, and must never be flagged."""

    def __init__(self, name: str):
        self.name = name
        self._rl = threading.RLock()
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _itl.active and self._owner != threading.get_ident():
            _itl.maybe_yield(f"rlock:{self.name}")
        got = self._rl.acquire(blocking, timeout)
        if got:
            me = threading.get_ident()
            if self._owner == me:
                self._count += 1        # re-entrant: no edge, no push
            else:
                self._owner = me
                self._count = 1
                _note_acquire(self, self.name)
        return got

    def release(self) -> None:
        me = threading.get_ident()
        if self._owner == me:
            if self._count > 1:
                self._count -= 1
                self._rl.release()
                return
            # final level: clear tracking BEFORE the inner release —
            # the instant it drops, another thread's acquire may set
            # _owner, so touching it afterwards would race
            self._owner = None
            self._count = 0
            _note_release(self)
        # non-owner misuse reaches here with tracking untouched and
        # raises from the real RLock
        self._rl.release()

    # Condition(wait) integration: fully release every recursion level
    # and restore it after, keeping the held-set in step (the stdlib
    # RLock provides these for exactly this purpose)
    def _release_save(self):
        _note_release(self)
        count, owner = self._count, self._owner
        self._owner = None
        self._count = 0
        return (self._rl._release_save(), count, owner)

    def _acquire_restore(self, state):
        inner, count, owner = state
        self._rl._acquire_restore(inner)
        self._owner = owner
        self._count = count
        _note_acquire(self, self.name)

    def _is_owned(self):
        return self._rl._is_owned()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<DepRLock {self.name!r}>"


class DepCondition:
    """Instrumented ``threading.Condition`` over a Dep lock.  The
    stdlib Condition drives the lock purely through acquire()/release()
    (or ``_release_save``/``_acquire_restore`` when the lock provides
    them), so wait() keeps the per-thread held-set correct: the lock
    leaves the set while the thread parks and re-enters on wakeup."""

    def __init__(self, name: str, lock=None):
        self._dep = lock if lock is not None else DepLock(name)
        self._cond = threading.Condition(self._dep)
        self.name = name

    # lock protocol (with cond: ...)
    def acquire(self, *a, **kw):
        return self._dep.acquire(*a, **kw)

    def release(self) -> None:
        self._dep.release()

    def __enter__(self):
        self._cond.__enter__()
        return self

    def __exit__(self, *exc):
        return self._cond.__exit__(*exc)

    # condvar protocol
    def wait(self, timeout: Optional[float] = None):
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self):
        return f"<DepCondition {self.name!r}>"


# ---------------------------------------------------- enable/report --
def enable() -> None:
    """Turn the checker on (refcounted — the ``analysis.lockdep`` conf
    knob, ``pytest --lockdep`` and the stress CLI each hold one
    reference).  Locks created while enabled are instrumented; locks
    created before stay plain (enable BEFORE building the clients you
    want checked)."""
    global enabled, _enable_count
    with _state.lock:
        _enable_count += 1
        enabled = True


def disable() -> None:
    """Drop one reference; the last disables recording.  The graph is
    kept for :func:`report` — :func:`reset` clears it."""
    global enabled, _enable_count
    with _state.lock:
        if _enable_count > 0:
            _enable_count -= 1
        if _enable_count == 0:
            enabled = False


def reset() -> None:
    """Clear the order graph and violation lists (not the refcount)."""
    global _state
    _state = _State()


@contextmanager
def scope():
    """Fresh graph for the duration (tests that build synthetic
    deadlocks must not pollute a ``--lockdep`` session's graph)."""
    global _state
    prev, _state = _state, _State()
    try:
        yield _state
    finally:
        _state = prev


def _find_cycles(adj: dict[str, set[str]]) -> list[list[str]]:
    """Cycle enumeration, deduped per node-set: every 2-cycle, plus one
    representative longer cycle per distinct set (the graph has tens of
    nodes, so plain DFS is fine)."""
    cycles: list[list[str]] = []
    seen: set[frozenset] = set()
    # self-edges (same class, distinct instances)
    for a, outs in adj.items():
        if a in outs:
            cycles.append([a, a])
            seen.add(frozenset((a,)))
    # 2-cycles first: they are the classic AB/BA report
    for a, outs in adj.items():
        for b in outs:
            if a != b and a in adj.get(b, ()):
                key = frozenset((a, b))
                if key not in seen:
                    seen.add(key)
                    cycles.append([a, b, a])
    # longer cycles: DFS from each node
    def dfs(start: str, node: str, path: list[str], visiting: set[str]):
        for nxt in adj.get(node, ()):
            if nxt == start and len(path) > 2:
                key = frozenset(path)
                if key not in seen:
                    seen.add(key)
                    cycles.append(path + [start])
            elif nxt not in visiting and len(path) < 8:
                visiting.add(nxt)
                dfs(start, nxt, path + [nxt], visiting)
                visiting.discard(nxt)

    for a in list(adj):
        dfs(a, a, [a], {a})
    return cycles


def report() -> dict:
    """The findings: ``cycles`` (each with every participating edge's
    acquisition stacks) and ``blocking`` violations, plus graph-size
    gauges.  ``clean(report())`` is the gate predicate."""
    st = _state
    with st.lock:
        adj = {k: set(v) for k, v in st.adj.items()}
        edges = dict(st.edges)
        blocking = list(st.blocking)
        classes = len(st.classes)
        acq = st.acquisitions
    out_cycles = []
    for path in _find_cycles(adj):
        evs = []
        for i in range(len(path) - 1):
            e = edges.get((path[i], path[i + 1]))
            if e is not None:
                evs.append(e.as_dict())
        out_cycles.append({
            "kind": ("inconsistent_order" if len(path) == 3
                     else "self_order" if len(path) == 2
                     else "cycle"),
            "path": path,
            "edges": evs,
        })
    return {"classes": classes, "edges": len(edges),
            "acquisitions": acq, "cycles": out_cycles,
            "blocking": blocking}


def clean(rep: Optional[dict] = None) -> bool:
    rep = rep if rep is not None else report()
    return not rep["cycles"] and not rep["blocking"]


def format_report(rep: Optional[dict] = None) -> str:
    """Human-readable findings (the check.sh / pytest summary)."""
    rep = rep if rep is not None else report()
    lines = [f"lockdep: {rep['classes']} lock classes, "
             f"{rep['edges']} order edges, "
             f"{rep['acquisitions']} acquisitions"]
    for c in rep["cycles"]:
        lines.append(f"\n=== {c['kind']}: {' -> '.join(c['path'])} ===")
        for e in c["edges"]:
            lines.append(f"--- {e['from']} -> {e['to']} "
                         f"(thread {e['thread']}, seen {e['count']}x)")
            if e.get("held_stack"):
                lines.append(f"  {e['from']} acquired at:")
                lines.append("    " +
                             e["held_stack"].strip().replace("\n", "\n    "))
            lines.append(f"  {e['to']} acquired at:")
            lines.append("    " + e["stack"].strip().replace("\n", "\n    "))
    for b in rep["blocking"]:
        lines.append(f"\n=== held across blocking: {b['lock']} held at "
                     f"{b['call']} (thread {b['thread']}) ===")
        if b.get("held_stack"):
            lines.append(f"  {b['lock']} acquired at:")
            lines.append("    " +
                         b["held_stack"].strip().replace("\n", "\n    "))
        lines.append("  blocking call at:")
        lines.append("    " + b["stack"].strip().replace("\n", "\n    "))
    if clean(rep):
        lines.append("lockdep: clean (no cycles, no held-across-blocking)")
    return "\n".join(lines)
