"""Eraser-style lockset data-race detector over the declared
shared-state surface (Savage et al., SOSP 1997 — the dynamic complement
of lockdep's lock-ORDER checking).

lockdep (PR 8) proves the locks that ARE taken nest consistently; it
says nothing about state touched with the wrong lock, or no lock at
all.  This module closes that gap for every field a concurrent layer
*declares*:

  * ``shared()`` — a class-body marker for cross-thread mutable
    attributes.  DISABLED (the default) the marker deletes itself at
    class creation, so the attribute is a plain instance attribute and
    the hot path pays literally nothing (the ``bench.py --smoke``
    ``races_overhead`` gate holds this to <1% of the produce budget —
    same contract as the locks factory).  ENABLED, a :class:`Guarded`
    data descriptor is installed on the class (values keep living in
    the instance ``__dict__``/slot, so enable/disable retrofit cleanly
    onto already-imported classes) and every attribute get/set records
    ``(thread, current lockset)`` from lockdep's per-thread held-stack.
  * ``register_slots()`` — the same declaration for ``__slots__``
    classes: the member descriptor is wrapped while enabled and
    restored on disable.
  * ``shared_dict()`` / ``shared_list()`` / ``shared_counter()`` —
    factories for the container idioms where the interesting mutation
    is a METHOD call, invisible to an attribute descriptor
    (``self.acked.append(...)`` reads the attribute): enabled they
    return :class:`SharedDict`/:class:`SharedList`/:class:`SharedCounter`
    wrappers whose mutators record WRITE accesses; disabled they return
    the plain ``dict``/``list``/counter.

Each declared variable walks the classic lockset state machine:

  VIRGIN --first access--> EXCLUSIVE --2nd thread read--> SHARED
                               |                            |
                          2nd thread write               write
                               v                            v
                         SHARED_MODIFIED <------------------+

The candidate set C(v) is initialized to the accessing thread's held
lockset when the variable leaves EXCLUSIVE and refined by intersection
on every subsequent access.  A WRITE with C(v) empty in
SHARED_MODIFIED is reported with both access stacks (the racing
write's and the other threads' first-access stacks) — reads never
report (the ``read-shared`` pattern is legal), they only refine, so an
unlocked reader still convicts the *next* write.  One report per
variable.

``relaxed=True`` declarations are tracked through the same machine but
reported separately and never fail the gate — for judged
single-writer/snapshot-reader patterns; every relaxed declaration
carries a written justification at the use site (the shared-state lint
rule's analog of the pragma).

Enable paths: ``races.enable()`` (refcounted; also holds a lockdep
reference — locksets come from its held-stack, so the instrumented
lock wrappers must be live), the ``analysis.races`` conf knob,
``pytest --races``, ``python -m librdkafka_tpu.analysis races``.
"""
from __future__ import annotations

import threading
import traceback
from contextlib import contextmanager
from typing import Optional

from . import interleave as _itl
from . import lockdep

#: master switch — declaration factories consult this at CREATION /
#: install time; Guarded descriptors are only installed while enabled
enabled = False

STACK_DEPTH = 16

_enable_count = 0
_reg_lock = threading.Lock()

#: declared variables: ("attr", cls, attr, var, relaxed) for plain
#: classes, ("slot", cls, attr, var, relaxed, member) for __slots__
_registry: list[tuple] = []

#: lock id -> class name, for readable candidate sets in reports
_lock_names: dict[int, str] = {}


class _VarState:
    """Per-variable lockset state (keyed by (id(owner), attr))."""

    __slots__ = ("var", "state", "owner_ident", "lockset", "threads",
                 "first_stacks", "reported", "relaxed", "written")

    def __init__(self, var: str, relaxed: bool):
        self.var = var
        self.state = "virgin"
        self.owner_ident: Optional[int] = None
        self.lockset: Optional[frozenset] = None    # candidate set C(v)
        self.threads: dict[int, str] = {}           # ident -> name
        self.first_stacks: dict[str, str] = {}      # thread name -> stack
        self.reported = False
        self.relaxed = relaxed
        self.written = False


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.vars: dict[tuple, _VarState] = {}
        self.races: list[dict] = []
        self.relaxed_races: list[dict] = []
        self.accesses = 0


_state = _State()

#: thread identity for the state machine: a monotonic per-thread token
#: (threading.local dies with its thread) — NOT get_ident(), whose
#: pthread ids are recycled the moment a thread exits, which would
#: alias a new thread onto a dead owner and silently keep a variable
#: EXCLUSIVE (a false negative the 0130 suite reproduces)
_tl = threading.local()
_tid_lock = threading.Lock()
_tid_next = 0


def _tid() -> int:
    t = getattr(_tl, "tid", None)
    if t is None:
        global _tid_next
        with _tid_lock:
            _tid_next += 1
            t = _tl.tid = _tid_next
    return t


def _capture() -> str:
    return "".join(traceback.format_stack(limit=STACK_DEPTH)[:-2])


def _held_set() -> frozenset:
    """The current thread's lockset, as lock-instance ids (Eraser
    refines on instances: Toppar A's lock does not protect Toppar B's
    queue even though both are class ``kafka.toppar``)."""
    held = lockdep.held_locks()
    if not held:
        return frozenset()
    for obj, name in held:
        _lock_names.setdefault(id(obj), name)
    return frozenset(id(obj) for obj, _n in held)


def _lockset_names(ls) -> list:
    return sorted({_lock_names.get(i, "?") for i in ls}) if ls else []


def reset_var(key: tuple, var: str, relaxed: bool) -> None:
    """Forget a variable's history (first initialization / container
    construction) — guards against id() reuse of dead instances
    bleeding SHARED state into a fresh object."""
    st = _state
    with st.lock:
        st.vars[key] = _VarState(var, relaxed)


def record(key: tuple, var: str, is_write: bool, relaxed: bool,
           cls_name: str = "") -> None:
    """One access to declared variable ``key``; the heart of the
    detector.  Called only while enabled (callers guard)."""
    ident = _tid()
    lockset = _held_set()
    st = _state
    report = None
    with st.lock:
        st.accesses += 1
        vs = st.vars.get(key)
        if vs is None:
            vs = st.vars[key] = _VarState(var, relaxed)
        tname = vs.threads.get(ident)
        if tname is None:
            tname = threading.current_thread().name
            vs.threads[ident] = tname
            if len(vs.first_stacks) < 8:       # bounded per variable
                vs.first_stacks[tname] = _capture()
        if vs.state == "virgin":
            vs.state = "exclusive"
            vs.owner_ident = ident
            vs.written = is_write
        elif vs.state == "exclusive":
            if ident == vs.owner_ident:
                vs.written = vs.written or is_write
            else:
                # second thread: leave EXCLUSIVE; C(v) starts as the
                # locks held right now and refines from here on.  A
                # read lands in SHARED even when the owner wrote (the
                # classic diagram): the single-writer/multi-reader
                # pattern convicts only when the owner writes AGAIN
                # with the candidate set already empty.
                vs.lockset = lockset
                vs.state = "shared_modified" if is_write else "shared"
                vs.written = vs.written or is_write
        else:
            vs.lockset = (lockset if vs.lockset is None
                          else vs.lockset & lockset)
            if is_write:
                vs.written = True
                if vs.state == "shared":
                    vs.state = "shared_modified"
        if (is_write and vs.state == "shared_modified"
                and not vs.lockset and not vs.reported):
            vs.reported = True
            report = {
                "kind": "empty_lockset_write",
                "var": vs.var,
                "class": cls_name,
                "state": vs.state,
                "relaxed": vs.relaxed,
                "thread": threading.current_thread().name,
                "threads": sorted(set(vs.threads.values())),
                "lockset": _lockset_names(lockset),
                "stack": _capture(),
                "other_stacks": [
                    {"thread": t, "stack": s}
                    for t, s in vs.first_stacks.items()
                    if t != threading.current_thread().name],
            }
            (st.relaxed_races if vs.relaxed else st.races).append(report)


# ------------------------------------------------------- descriptors --
class Guarded:
    """Data descriptor recording every get/set of a declared attribute.
    Values live in the instance ``__dict__`` (or the wrapped slot), so
    installing/removing the descriptor never migrates state.  Also a
    schedule-explorer yield point: a preemption between the recorded
    read and the following write is exactly the lost-update window."""

    __slots__ = ("var", "attr", "relaxed", "slot", "cls_name")

    def __init__(self, var: str, attr: str, relaxed: bool,
                 slot=None, cls_name: str = ""):
        self.var = var
        self.attr = attr
        self.relaxed = relaxed
        self.slot = slot            # member descriptor for __slots__
        self.cls_name = cls_name

    def __set_name__(self, owner, name):    # direct use as class var
        _register_attr(owner, name, self.var or None, self.relaxed)

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if self.slot is not None:
            val = self.slot.__get__(obj, objtype)
        else:
            try:
                val = obj.__dict__[self.attr]
            except KeyError:
                raise AttributeError(self.attr) from None
        if enabled:
            record((id(obj), self.attr), self.var, False, self.relaxed,
                   self.cls_name)
            if _itl.active:
                _itl.maybe_yield(f"get:{self.var}")
        return val

    def __set__(self, obj, value):
        if self.slot is not None:
            try:
                self.slot.__get__(obj)
                first = False
            except AttributeError:
                first = True
            if _itl.active and not first:
                _itl.maybe_yield(f"set:{self.var}")
            self.slot.__set__(obj, value)
        else:
            first = self.attr not in obj.__dict__
            if _itl.active and not first:
                _itl.maybe_yield(f"set:{self.var}")
            obj.__dict__[self.attr] = value
        if enabled:
            if first:
                # __init__ assignment: fresh state (also defuses id()
                # reuse of a dead instance)
                reset_var((id(obj), self.attr), self.var, self.relaxed)
            record((id(obj), self.attr), self.var, True, self.relaxed,
                   self.cls_name)

    def __delete__(self, obj):
        if enabled:
            record((id(obj), self.attr), self.var, True, self.relaxed,
                   self.cls_name)
        if self.slot is not None:
            self.slot.__delete__(obj)
        else:
            obj.__dict__.pop(self.attr, None)


class shared:
    """Class-body declaration of a cross-thread mutable attribute::

        class OpQueue:
            _items = shared("queue.opq.items")

    Disabled at class creation, the marker deletes itself — the
    attribute is a plain instance attribute.  The declaration is
    registered either way, so ``enable()`` can retrofit a
    :class:`Guarded` descriptor onto the already-created class (and
    ``disable()`` remove it again)."""

    def __init__(self, name: Optional[str] = None, *,
                 relaxed: bool = False):
        self.name = name
        self.relaxed = relaxed

    def __set_name__(self, owner, attr):
        _register_attr(owner, attr, self.name, self.relaxed)


def _register_attr(owner, attr: str, name: Optional[str],
                   relaxed: bool) -> None:
    var = name or f"{owner.__name__}.{attr}"
    with _reg_lock:
        _registry.append(("attr", owner, attr, var, relaxed))
        if enabled:
            setattr(owner, attr,
                    Guarded(var, attr, relaxed, cls_name=owner.__name__))
        else:
            # resolve to a plain attribute: zero cost until enabled
            if attr in owner.__dict__:
                delattr(owner, attr)


def register_slots(cls, *attrs: str, relaxed: bool = False,
                   prefix: Optional[str] = None) -> None:
    """Declare ``__slots__`` members of ``cls`` as shared state (a
    class-body ``shared()`` marker would collide with the slot
    descriptor).  Call after the class definition::

        register_slots(Toppar, "msgq_bytes", "inflight")
    """
    with _reg_lock:
        for attr in attrs:
            member = cls.__dict__[attr]     # the member_descriptor
            var = f"{prefix or cls.__name__}.{attr}"
            _registry.append(("slot", cls, attr, var, relaxed, member))
            if enabled:
                setattr(cls, attr, Guarded(var, attr, relaxed,
                                           slot=member,
                                           cls_name=cls.__name__))


def _install_all() -> None:
    for ent in _registry:
        if ent[0] == "attr":
            _k, cls, attr, var, relaxed = ent
            setattr(cls, attr, Guarded(var, attr, relaxed,
                                       cls_name=cls.__name__))
        else:
            _k, cls, attr, var, relaxed, member = ent
            setattr(cls, attr, Guarded(var, attr, relaxed, slot=member,
                                       cls_name=cls.__name__))


def _uninstall_all() -> None:
    for ent in _registry:
        if ent[0] == "attr":
            _k, cls, attr, _var, _relaxed = ent
            if isinstance(cls.__dict__.get(attr), Guarded):
                delattr(cls, attr)
        else:
            _k, cls, attr, _var, _relaxed, member = ent
            setattr(cls, attr, member)


# -------------------------------------------------------- containers --
class SharedList(list):
    """List whose mutators record WRITE accesses (ledger idiom:
    ``oracle.acked.append(...)``) and whose readers record reads."""

    def __init__(self, var: str, relaxed: bool = False, seq=()):
        super().__init__(seq)
        self._var = var
        self._relaxed = relaxed
        reset_var((id(self),), var, relaxed)

    def _w(self):
        if enabled:
            record((id(self),), self._var, True, self._relaxed,
                   "SharedList")

    def _r(self):
        if enabled:
            record((id(self),), self._var, False, self._relaxed,
                   "SharedList")

    def append(self, x):
        self._w()
        super().append(x)

    def extend(self, it):
        self._w()
        super().extend(it)

    def insert(self, i, x):
        self._w()
        super().insert(i, x)

    def pop(self, i=-1):
        self._w()
        return super().pop(i)

    def remove(self, x):
        self._w()
        super().remove(x)

    def clear(self):
        self._w()
        super().clear()

    def __setitem__(self, i, v):
        self._w()
        super().__setitem__(i, v)

    def __iter__(self):
        self._r()
        return super().__iter__()

    def __len__(self):
        self._r()
        return super().__len__()

    def __getitem__(self, i):
        self._r()
        return super().__getitem__(i)


class SharedDict(dict):
    """Dict whose mutators record WRITE accesses (table idiom:
    ``self.txns[txn] = "open"``)."""

    def __init__(self, var: str, relaxed: bool = False, m=()):
        super().__init__(m)
        self._var = var
        self._relaxed = relaxed
        reset_var((id(self),), var, relaxed)

    def _w(self):
        if enabled:
            record((id(self),), self._var, True, self._relaxed,
                   "SharedDict")

    def _r(self):
        if enabled:
            record((id(self),), self._var, False, self._relaxed,
                   "SharedDict")

    def __setitem__(self, k, v):
        self._w()
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._w()
        super().__delitem__(k)

    def pop(self, *a):
        self._w()
        return super().pop(*a)

    def popitem(self):
        self._w()
        return super().popitem()

    def setdefault(self, k, d=None):
        self._w()
        return super().setdefault(k, d)

    def update(self, *a, **kw):
        self._w()
        super().update(*a, **kw)

    def clear(self):
        self._w()
        super().clear()

    def __getitem__(self, k):
        self._r()
        return super().__getitem__(k)

    def get(self, k, d=None):
        self._r()
        return super().get(k, d)

    def __contains__(self, k):
        self._r()
        return super().__contains__(k)

    def __len__(self):
        self._r()
        return super().__len__()

    def __iter__(self):
        self._r()
        return super().__iter__()

    def items(self):
        self._r()
        return super().items()

    def keys(self):
        self._r()
        return super().keys()

    def values(self):
        self._r()
        return super().values()


class _PlainCounter:
    """The disabled counter: a bare int cell (no recording)."""

    __slots__ = ("v",)

    def __init__(self, v: int = 0):
        self.v = v

    def add(self, n: int = 1) -> None:
        self.v += n

    @property
    def value(self) -> int:
        return self.v

    def __int__(self) -> int:
        return self.v


class SharedCounter(_PlainCounter):
    """Counter whose ``add`` records a write (the ``+=`` idiom, as an
    object for call sites that want an explicit cell)."""

    __slots__ = ("_var", "_relaxed")

    def __init__(self, var: str, relaxed: bool = False, v: int = 0):
        super().__init__(v)
        self._var = var
        self._relaxed = relaxed
        reset_var((id(self),), var, relaxed)

    def add(self, n: int = 1) -> None:
        if enabled:
            record((id(self),), self._var, True, self._relaxed,
                   "SharedCounter")
            if _itl.active:
                _itl.maybe_yield(f"counter:{self._var}")
        self.v += n

    @property
    def value(self) -> int:
        if enabled:
            record((id(self),), self._var, False, self._relaxed,
                   "SharedCounter")
        return self.v


def shared_list(var: str, relaxed: bool = False):
    """A list declared as shared state — plain ``list`` when the
    detector is off (creation-time decision, like the locks factory)."""
    if enabled:
        return SharedList(var, relaxed)
    return []


def shared_dict(var: str, relaxed: bool = False):
    if enabled:
        return SharedDict(var, relaxed)
    return {}


def shared_counter(var: str, relaxed: bool = False):
    if enabled:
        return SharedCounter(var, relaxed)
    return _PlainCounter()


# ------------------------------------------------------ enable/report --
def enable() -> None:
    """Turn the detector on (refcounted).  Installs Guarded descriptors
    on every registered class and holds a lockdep reference — the
    lockset of each access IS lockdep's per-thread held-stack, so the
    instrumented lock wrappers must be live.  Like lockdep: enable
    BEFORE building the clients you want swept (containers and locks
    created earlier stay plain)."""
    global enabled, _enable_count
    with _reg_lock:
        _enable_count += 1
        if _enable_count == 1:
            enabled = True
            _install_all()
    lockdep.enable()


def disable() -> None:
    """Drop one reference; the last uninstalls the descriptors.  State
    survives for :func:`report`; :func:`reset` clears it."""
    global enabled, _enable_count
    with _reg_lock:
        if _enable_count > 0:
            _enable_count -= 1
            lockdep.disable()
        if _enable_count == 0:
            enabled = False
            _uninstall_all()


def reset() -> None:
    global _state
    _state = _State()


@contextmanager
def scope():
    """Fresh findings state for the duration (tests that plant races
    must not pollute a ``--races`` session's report)."""
    global _state
    prev, _state = _state, _State()
    try:
        yield _state
    finally:
        _state = prev


def report() -> dict:
    st = _state
    with st.lock:
        states = {}
        for vs in st.vars.values():
            states[vs.state] = states.get(vs.state, 0) + 1
        return {"vars": len(st.vars),
                "accesses": st.accesses,
                "states": states,
                "races": list(st.races),
                "relaxed_races": list(st.relaxed_races)}


def clean(rep: Optional[dict] = None) -> bool:
    rep = rep if rep is not None else report()
    return not rep["races"]


def format_report(rep: Optional[dict] = None) -> str:
    rep = rep if rep is not None else report()
    lines = [f"races: {rep['vars']} shared vars, "
             f"{rep['accesses']} accesses, states {rep['states']}"]
    for r in rep["races"] + [dict(x, _relaxed_note=True)
                             for x in rep["relaxed_races"]]:
        tag = " (RELAXED, informational)" if r.get("_relaxed_note") else ""
        lines.append(f"\n=== empty-lockset write: {r['var']} "
                     f"[{r['class']}]{tag} ===")
        lines.append(f"  threads: {', '.join(r['threads'])}; racing "
                     f"write on {r['thread']} held {r['lockset'] or '{}'}")
        lines.append(f"  write at:")
        lines.append("    " + r["stack"].strip().replace("\n", "\n    "))
        for o in r["other_stacks"]:
            lines.append(f"  {o['thread']} first accessed at:")
            lines.append("    " +
                         o["stack"].strip().replace("\n", "\n    "))
    if clean(rep):
        lines.append("races: clean (no empty-lockset writes)")
    return "\n".join(lines)
