"""Central lock factory: every concurrent layer creates its primitives
here so the lockdep checker can swap in instrumented wrappers.

Disabled (the default), each function returns the PLAIN ``threading``
primitive — the choice is made once, at creation time, so production
code pays literally nothing per acquisition (the bench.py --smoke
``lockdep_overhead`` gate holds this to <1% of the produce budget).
Enabled (``analysis.lockdep=true`` conf knob, ``pytest --lockdep``, or
``python -m librdkafka_tpu.analysis stress``), the same call sites get
:class:`~.lockdep.DepLock`-family wrappers and every acquisition feeds
the global lock-order graph.

Names are lock CLASSES, not instances: all Toppar locks share
``"kafka.toppar"`` so an ordering inversion between any two broker
threads is visible regardless of which partitions were involved.
The lint's ``lock-factory`` rule keeps new lock sites in ``client/``,
``ops/engine.py``, ``ops/tpu.py``, ``mock/`` and ``chaos/`` from
bypassing this factory.
"""
from __future__ import annotations

import threading

from . import lockdep


def new_lock(name: str):
    """A mutex for lock class ``name`` — ``threading.Lock()`` when the
    checker is off, an instrumented :class:`~.lockdep.DepLock` when
    on."""
    if lockdep.enabled:
        return lockdep.DepLock(name)
    return threading.Lock()


def new_rlock(name: str):
    """A re-entrant mutex (``threading.RLock`` / ``DepRLock``) —
    re-entrant acquisition is never reported as an ordering edge."""
    if lockdep.enabled:
        return lockdep.DepRLock(name)
    return threading.RLock()


def new_cond(name: str, lock=None):
    """A condition variable, optionally sharing ``lock`` (itself
    factory-made so waits keep the held-set coherent)."""
    if lockdep.enabled:
        return lockdep.DepCondition(name, lock)
    return threading.Condition(lock)
