"""Kafka wire-protocol primitive types and declarative schemas.

The declarative-schema equivalent of the reference's read/write macro layer
(rd_kafka_buf_read_* / rd_kafka_buf_write_* in src/rdkafka_buf.h:162-302):
every request/response is described once as a Schema and both the client
and the in-process mock broker build/parse through it, so the two sides
cannot drift. Underflow raises BufUnderflow — the same "goto err_parse"
error contract.
"""
from __future__ import annotations

import struct
from typing import Any, Optional

from ..utils.buf import SegBuf, Slice


class _Prim:
    fmt: str

    def __init__(self):
        self.size = struct.calcsize(self.fmt)

    def write(self, buf: SegBuf, val) -> None:
        buf.write(struct.pack(self.fmt, val))

    def read(self, sl: Slice):
        return struct.unpack(self.fmt, sl.read(self.size))[0]


class _Int8(_Prim):
    fmt = ">b"


class _Int16(_Prim):
    fmt = ">h"


class _Int32(_Prim):
    fmt = ">i"


class _Int64(_Prim):
    fmt = ">q"


class _UInt32(_Prim):
    fmt = ">I"


class _Float64(_Prim):
    fmt = ">d"


class _Boolean:
    def write(self, buf, val):
        buf.write(b"\x01" if val else b"\x00")

    def read(self, sl):
        return sl.read(1) != b"\x00"


class _String:
    """Non-null string: int16 length + utf8 bytes."""

    def write(self, buf, val: str):
        b = val.encode("utf-8")
        buf.write_i16(len(b))
        buf.write(b)

    def read(self, sl) -> str:
        n = sl.read_i16()
        if n < 0:
            raise ValueError("unexpected null string")
        return sl.read(n).decode("utf-8")


class _NullableString:
    def write(self, buf, val: Optional[str]):
        if val is None:
            buf.write_i16(-1)
        else:
            b = val.encode("utf-8")
            buf.write_i16(len(b))
            buf.write(b)

    def read(self, sl) -> Optional[str]:
        n = sl.read_i16()
        return None if n < 0 else sl.read(n).decode("utf-8")


class _Bytes:
    """Nullable bytes: int32 length (-1 = null) + bytes."""

    #: payloads at or above this ride as spliced read-only segments
    #: (no copy into the write buffer; they go to the socket via the
    #: SegWriter iovec path) — RecordBatch bytes in Produce requests
    #: and Fetch responses are the case that matters
    SPLICE_MIN = 4096

    def write(self, buf, val: Optional[bytes]):
        if val is None:
            buf.write_i32(-1)
        else:
            buf.write_i32(len(val))
            if len(val) >= self.SPLICE_MIN:
                buf.push_ro(val)
            else:
                buf.write(val)

    def read(self, sl) -> Optional[bytes]:
        n = sl.read_i32()
        if n < 0:
            return None
        if n >= self.SPLICE_MIN:
            # large payloads (RecordBatch blobs) come out as views into
            # the response frame — the codec/parse layers consume them
            # through the buffer protocol without a flat copy
            return sl.view(n)
        return sl.read(n)


Int8, Int16, Int32, Int64 = _Int8(), _Int16(), _Int32(), _Int64()
UInt32, Float64 = _UInt32(), _Float64()
Boolean = _Boolean()
String, NullableString, Bytes = _String(), _NullableString(), _Bytes()


class Array:
    """int32 count (-1 = null) + elements."""

    def __init__(self, elem):
        self.elem = elem

    def write(self, buf, val):
        if val is None:
            buf.write_i32(-1)
            return
        buf.write_i32(len(val))
        for v in val:
            self.elem.write(buf, v)

    def read(self, sl):
        n = sl.read_i32()
        if n < 0:
            return None
        if n > sl.remains():  # count cannot exceed remaining bytes
            raise ValueError(f"array count {n} exceeds buffer")
        return [self.elem.read(sl) for _ in range(n)]


class Schema:
    """Named-field record; values are plain dicts. ``defaults`` supplies
    values for fields a caller may omit (e.g. flags added by a later
    protocol version, so version-agnostic request bodies keep working)."""

    def __init__(self, *fields: tuple[str, Any],
                 defaults: dict | None = None):
        self.fields = fields
        self.defaults = defaults or {}

    def write(self, buf, val: dict):
        for name, typ in self.fields:
            if name in val:
                typ.write(buf, val[name])
            else:                   # KeyError unless a default exists
                typ.write(buf, self.defaults[name])

    def read(self, sl) -> dict:
        return {name: typ.read(sl) for name, typ in self.fields}


def encode(schema, val: dict) -> bytes:
    buf = SegBuf()
    schema.write(buf, val)
    return buf.as_bytes()


def decode(schema, data) -> dict:
    sl = data if isinstance(data, Slice) else Slice(data)
    return schema.read(sl)
