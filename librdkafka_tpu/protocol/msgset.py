"""MessageSet (RecordBatch) v2 writer + reader, plus legacy v0/v1.

This is the north-star seam (SURVEY.md §3.2): the reference builds each
partition batch in rd_kafka_msgset_create_ProduceRequest
(src/rdkafka_msgset_writer.c:1418) — write header, write records, compress
(writer_compress :1129), rewind + splice the compressed segment
(:1191-1203), then finalize by back-patching the v2 header and computing
CRC32C over [Attributes..end] (:1252,1230). The consumer side parses and
verifies in rd_kafka_msgset_reader.c (:950-1016, decompress :258-530).

The writer here is deliberately split into three phases so that *many*
partition batches can be compressed/checksummed in ONE batched codec-
provider call (the TPU offload axis):

    w = MsgsetWriterV2(...); w.build(msgs)       # phase 1: frame records
    blobs = provider.compress_many(codec, [w.records_bytes ...])
    wire = w.finalize(compressed=blob)           # phase 3: splice + CRC

``finalize(None)`` is the uncompressed path. Single-shot ``write_batch()``
wraps all three for the simple case.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

from ..utils import varint
from ..utils.buf import SegBuf, Slice
from ..utils.crc import crc32
from ..utils.crc import crc32c as _crc32c_py
from . import proto
from .proto import (ATTR_CODEC_MASK, ATTR_CONTROL, ATTR_TRANSACTIONAL,
                    CODEC_IDS, CODEC_NAMES)

_crc32c_fast = None


def crc32c(data, crc: int = 0) -> int:
    """CRC32C via the native library (utils/crc.py's byte loop is a
    conformance oracle, never a hot path — VERDICT r1 weak #1/#2)."""
    global _crc32c_fast
    if _crc32c_fast is None:
        try:
            from ..ops.cpu import crc32c as _n
            _n(b"")          # force the native build now
            _crc32c_fast = _n
        except Exception:
            _crc32c_fast = _crc32c_py
    return _crc32c_fast(bytes(data), crc)


# precomputed zig-zag varints for common small framing values
_VI_CACHE = {v: varint.enc_i64(v) for v in range(-64, 8192)}

_frame_native = None     # resolved lazily: ops.cpu.frame_v2 | False


@dataclass
class Record:
    """A parsed (or to-be-written) record."""
    key: Optional[bytes] = None
    value: Optional[bytes] = None
    headers: Sequence[tuple[str, Optional[bytes]]] = ()
    timestamp: int = -1          # ms since epoch; -1 = now/unset
    offset: int = -1             # absolute offset (reader fills this)
    # batch-level context the reader attaches:
    msgver: int = 2
    is_control: bool = False
    is_transactional: bool = False
    producer_id: int = -1
    timestamp_type: int = proto.TSTYPE_CREATE_TIME


# ===================================================================== v2 ==

class MsgsetWriterV2:
    """RecordBatch v2 writer with deferred compression/CRC."""

    def __init__(self, *, base_offset: int = 0, producer_id: int = -1,
                 producer_epoch: int = -1, base_sequence: int = -1,
                 transactional: bool = False, control: bool = False,
                 codec: Optional[str] = None,
                 timestamp_type: int = proto.TSTYPE_CREATE_TIME):
        self.base_offset = base_offset
        self.producer_id = producer_id
        self.producer_epoch = producer_epoch
        self.base_sequence = base_sequence
        self.transactional = transactional
        # control batches (COMMIT/ABORT transaction markers) are broker-
        # written; the mock cluster's txn coordinator builds them here
        self.control = control
        self.codec = None if codec in (None, "none") else codec
        self.timestamp_type = timestamp_type
        self.records_bytes: bytes = b""
        self.record_count = 0
        self.first_timestamp = -1
        self.max_timestamp = -1
        self._wire: Optional[bytearray] = None

    # -- phase 1: frame records (uncompressed) ---------------------------
    def build(self, msgs, now_ms: int) -> "MsgsetWriterV2":
        """Frame all records (reference hot loop:
        rd_kafka_msgset_writer_write_msg_v2, rdkafka_msgset_writer.c:653).
        Headerless batches take the native single-call path (GIL released
        during framing); batches with headers use the Python framer."""
        global _frame_native
        if not isinstance(msgs, (list, tuple)):
            msgs = list(msgs)       # may be iterated twice (header fallback)
        if _frame_native is None:
            try:
                from ..ops.cpu import frame_v2 as _f
                _f(b"", [], [], [])
                _frame_native = _f
            except Exception:
                _frame_native = False
        if _frame_native:
            parts = []
            klens: list[int] = []
            vlens: list[int] = []
            tds: list[int] = []
            first_ts = -1
            max_ts = -1
            for m in msgs:
                if m.headers:
                    break               # headers: python framer
                ts = m.timestamp if m.timestamp and m.timestamp > 0 else now_ms
                if first_ts < 0:
                    first_ts = ts
                if ts > max_ts:
                    max_ts = ts
                tds.append(ts - first_ts)
                k = m.key
                if k is None:
                    klens.append(-1)
                else:
                    klens.append(len(k))
                    parts.append(k)
                v = m.value
                if v is None:
                    vlens.append(-1)
                else:
                    vlens.append(len(v))
                    parts.append(v)
            else:
                if not tds:
                    raise ValueError("empty batch")
                self.records_bytes = _frame_native(
                    b"".join(parts), klens, vlens, tds)
                self.record_count = len(tds)
                self.first_timestamp = first_ts
                self.max_timestamp = max_ts
                return self
        return self._build_py(msgs, now_ms)

    def build_arena(self, batch, now_ms: int) -> "MsgsetWriterV2":
        """Frame a fast-lane ArenaBatch: ONE native call straight off the
        arena's buffers, zero per-record Python work (the reference's
        zero-allocation hot loop, rdkafka_msgset_writer.c:653).  The
        all-default shape (no explicit timestamps, no headers) frames
        with every delta zero; widened runs carry per-record timestamps
        (0 = batch build time) and pre-encoded header blobs in side
        arrays, framed by the run-native framer in one call."""
        if batch.tss is None and batch.hbuf is None:
            from ..ops.cpu import frame_v2_raw
            self.records_bytes = frame_v2_raw(batch.base, batch.klens,
                                              batch.vlens, batch.count)
            self.first_timestamp = now_ms
            self.max_timestamp = now_ms
        else:
            from ..ops.cpu import frame_v2_run
            (self.records_bytes, self.first_timestamp,
             self.max_timestamp) = frame_v2_run(
                batch.base, batch.klens, batch.vlens, batch.count, now_ms,
                batch.tss, batch.hbuf, batch.hlens)
        self.record_count = batch.count
        return self

    def _build_py(self, msgs, now_ms: int) -> "MsgsetWriterV2":
        rb = bytearray()
        body = bytearray()            # reused scratch for each record body
        cache = _VI_CACHE
        enc = varint.enc_i64
        count = 0
        first_ts = -1
        max_ts = -1
        for m in msgs:
            ts = m.timestamp if m.timestamp and m.timestamp > 0 else now_ms
            if first_ts < 0:
                first_ts = ts
            if ts > max_ts:
                max_ts = ts
            del body[:]
            body.append(0)                    # record attributes (unused)
            d = ts - first_ts
            body += cache.get(d) or enc(d)    # timestamp delta
            body += cache.get(count) or enc(count)   # offset delta
            key = m.key
            if key is None:
                body.append(1)                # varint(-1)
            else:
                n = len(key)
                body += cache.get(n) or enc(n)
                body += key
            value = m.value
            if value is None:
                body.append(1)                # varint(-1)
            else:
                n = len(value)
                body += cache.get(n) or enc(n)
                body += value
            hdrs = m.headers
            if hdrs:
                body += cache.get(len(hdrs)) or enc(len(hdrs))
                for hk, hv in hdrs:
                    hkb = hk.encode() if isinstance(hk, str) else hk
                    body += cache.get(len(hkb)) or enc(len(hkb))
                    body += hkb
                    if hv is None:
                        body.append(1)
                    else:
                        body += cache.get(len(hv)) or enc(len(hv))
                        body += hv
            else:
                body.append(0)                # varint(0) headers
            n = len(body)
            rb += cache.get(n) or enc(n)
            rb += body
            count += 1
        if count == 0:
            raise ValueError("empty batch")
        self.records_bytes = bytes(rb)
        self.record_count = count
        self.first_timestamp = first_ts
        self.max_timestamp = max_ts
        return self

    # -- phase 3: assemble header + (compressed) records, patch CRC ------
    # [BaseOffset i64][Length i32][PLeaderEpoch i32][Magic i8][CRC u32]
    # [Attrs i16][LastOffsetDelta i32][FirstTs i64][MaxTs i64][PID i64]
    # [PEpoch i16][BaseSeq i32][RecordCount i32] = 61 bytes
    _HDR = struct.Struct(">qiibIhiqqqhii")

    def assemble(self, compressed: Optional[bytes] = None) -> memoryview:
        """Build the wire batch with CRC=0; returns the CRC region
        ([Attributes..end]) so MANY batches can be checksummed in one
        provider call (reference computes per-batch at finalize,
        rdkafka_msgset_writer.c:1230-1252 — here the CRC joins the
        compress step on the batched offload axis)."""
        attrs = 0
        if compressed is not None:
            assert self.codec, "compressed bytes supplied without codec"
            attrs |= CODEC_IDS[self.codec]
        if self.timestamp_type == proto.TSTYPE_LOG_APPEND_TIME:
            attrs |= proto.ATTR_TIMESTAMP_TYPE
        if self.transactional:
            attrs |= ATTR_TRANSACTIONAL
        if self.control:
            attrs |= ATTR_CONTROL
        payload = compressed if compressed is not None else self.records_bytes
        wire = bytearray(self._HDR.pack(
            self.base_offset,
            (proto.V2_HEADER_SIZE - proto.V2_OF_PartitionLeaderEpoch)
            + len(payload),                              # Length
            # PartitionLeaderEpoch=0 exactly like the reference writer
            # (rdkafka_msgset_writer.c:368, KIP-101) — producers don't
            # know the epoch; 0 keeps wire bytes bit-identical to it.
            0, 2, 0, attrs, self.record_count - 1,
            self.first_timestamp, self.max_timestamp, self.producer_id,
            self.producer_epoch, self.base_sequence, self.record_count))
        wire += payload
        self._wire = wire
        return memoryview(wire)[proto.V2_OF_Attributes:]

    def patch_crc(self, crc: int) -> bytes:
        struct.pack_into(">I", self._wire, proto.V2_OF_CRC, crc)
        return bytes(self._wire)

    def finalize(self, compressed: Optional[bytes] = None,
                 crc: Optional[int] = None) -> bytes:
        """Return the wire RecordBatch. ``compressed`` is the codec output
        for ``records_bytes`` (None = write uncompressed); ``crc`` is a
        precomputed CRC32C over [Attributes..end] (None = compute here,
        native)."""
        region = self.assemble(compressed)
        return self.patch_crc(crc if crc is not None else crc32c(region))

    def write_batch(self, msgs, now_ms: int, compress_fn=None) -> bytes:
        """One-shot build+compress+finalize (CPU path convenience)."""
        self.build(msgs, now_ms)
        comp = None
        if self.codec and compress_fn is not None:
            c = compress_fn(self.records_bytes)
            if len(c) < len(self.records_bytes):  # only keep if smaller
                comp = c
            else:
                self.codec = None
        return self.finalize(comp)


@dataclass
class BatchInfo:
    """Parsed RecordBatch header (reader side)."""
    base_offset: int
    length: int
    magic: int
    crc: int
    attrs: int
    last_offset_delta: int
    first_timestamp: int
    max_timestamp: int
    producer_id: int
    producer_epoch: int
    base_sequence: int
    record_count: int
    codec: Optional[str]
    is_transactional: bool
    is_control: bool


class CrcMismatch(Exception):
    pass


def read_batch_header(sl: Slice) -> BatchInfo:
    base_offset = sl.read_i64()
    length = sl.read_i32()
    sl.read_i32()                 # partition leader epoch
    magic = sl.read_i8()
    if magic != 2:
        raise ValueError(f"not a v2 batch (magic={magic})")
    crc = sl.read_u32()
    attrs = sl.read_i16()
    last_delta = sl.read_i32()
    first_ts = sl.read_i64()
    max_ts = sl.read_i64()
    pid = sl.read_i64()
    epoch = sl.read_i16()
    base_seq = sl.read_i32()
    count = sl.read_i32()
    return BatchInfo(
        base_offset=base_offset, length=length, magic=magic, crc=crc,
        attrs=attrs, last_offset_delta=last_delta, first_timestamp=first_ts,
        max_timestamp=max_ts, producer_id=pid, producer_epoch=epoch,
        base_sequence=base_seq, record_count=count,
        codec=CODEC_NAMES.get(attrs & ATTR_CODEC_MASK),
        is_transactional=bool(attrs & ATTR_TRANSACTIONAL),
        is_control=bool(attrs & ATTR_CONTROL))


def parse_records_v2(info: BatchInfo, records_bytes: bytes) -> list[Record]:
    """Parse the (decompressed) records section of a v2 batch.

    Hot path: the varint field walk runs in native code (tk_parse_v2 in
    ops/native/codec.cpp — it was ~40% of consume time in Python);
    Python slices the key/value bytes and decodes headers only for the
    rare records that have them. Falls back to the pure-Python walk if
    the native library is unavailable."""
    if not isinstance(records_bytes, bytes):
        # Record.key/value must be owned bytes (this is the
        # inspection/test path; the consume hot path materializes
        # Messages straight off views via parse_fetch_messages_v2)
        records_bytes = bytes(records_bytes)
    try:
        return _parse_records_v2_native(info, records_bytes)
    except _NativeUnavailable:
        pass
    return _parse_records_v2_py(info, records_bytes)


class _NativeUnavailable(Exception):
    pass


def _parse_records_v2_native(info: BatchInfo,
                             records_bytes: bytes) -> list[Record]:
    import ctypes

    import numpy as np

    from ..ops import cpu as _cpu
    try:
        L = _cpu.lib()
    except Exception as e:
        raise _NativeUnavailable from e
    n = info.record_count
    if n <= 0:
        return []
    # a v2 record is >= 7 bytes; a forged record_count must not drive
    # the allocation (the Fetch payload is untrusted network data)
    if n > len(records_bytes) // 7 + 1:
        raise CrcMismatch(
            f"record_count {n} impossible for {len(records_bytes)} bytes")
    fields = np.empty((n, 8), dtype=np.int64)
    got = L.tk_parse_v2(
        records_bytes, len(records_bytes), n,
        fields.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if got != n:
        raise CrcMismatch(f"malformed v2 records: parsed {got} of {n}")
    log_append = bool(info.attrs & proto.ATTR_TIMESTAMP_TYPE)
    tstype = (proto.TSTYPE_LOG_APPEND_TIME if log_append
              else proto.TSTYPE_CREATE_TIME)
    base_ts = info.first_timestamp
    base_off = info.base_offset
    rows = fields.tolist()          # one bulk conversion, not n array reads
    out = []
    for ts_d, off_d, ko, kl, vo, vl, ho, nh in rows:
        key = records_bytes[ko:ko + kl] if kl >= 0 else None
        value = records_bytes[vo:vo + vl] if vl >= 0 else None
        headers = _parse_headers(records_bytes, ho, nh) if nh else []
        out.append(Record(
            key=key, value=value, headers=headers,
            timestamp=(info.max_timestamp if log_append
                       else base_ts + ts_d),
            offset=base_off + off_d, msgver=2,
            is_control=info.is_control,
            is_transactional=info.is_transactional,
            producer_id=info.producer_id, timestamp_type=tstype))
    return out


def parse_fetch_messages_v2(info: BatchInfo, records_bytes: bytes,
                            topic: str, partition: int,
                            fo: int) -> tuple[list, int]:
    """Fetch hot path: build delivery-ready client Message objects
    straight off the native field walk — no intermediate Record and no
    Message.__init__ (its two clock reads and len() calls cost ~1.5
    us/record against the ~2.5 us/msg consume budget). Records below
    ``fo`` are skipped here so the caller doesn't re-walk the list.
    Returns (messages, payload_bytes_total).

    Falls back to the Record path when the native walk is unavailable.
    (Late client import: the client layer imports protocol at module
    level, so this call-time import cannot cycle.)"""
    from ..client.msg import Message, MsgStatus

    import ctypes

    import numpy as np

    from ..ops import cpu as _cpu
    try:
        L = _cpu.lib()
    except Exception:
        out0, total0 = [], 0
        for r in parse_records_v2(info, records_bytes):
            if r.offset < fo:
                continue
            m = Message(topic, value=r.value, key=r.key,
                        partition=partition, headers=r.headers,
                        timestamp=r.timestamp)
            m.offset = r.offset
            m.timestamp_type = r.timestamp_type
            out0.append(m)
            total0 += m.size
        return out0, total0
    n = info.record_count
    if n <= 0:
        return [], 0
    if n > len(records_bytes) / 7 + 1:
        raise CrcMismatch(
            f"record_count {n} impossible for {len(records_bytes)} bytes")
    fields = np.empty((n, 8), dtype=np.int64)
    # records_bytes may be a memoryview into the response frame (the
    # zero-copy fetch path): hand the walk its address via numpy, which
    # wraps read-only buffers without copying
    src = np.frombuffer(records_bytes, dtype=np.uint8)
    got = L.tk_parse_v2(
        src.ctypes.data_as(ctypes.c_char_p), len(records_bytes), n,
        fields.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if got != n:
        raise CrcMismatch(f"malformed v2 records: parsed {got} of {n}")
    # LOG_APPEND_TIME: the broker stamps only MaxTimestamp; per-record
    # deltas still carry producer create times and must be IGNORED —
    # every record reports the batch append time (reference:
    # rdkafka_msgset_reader.c:902-908)
    log_append = bool(info.attrs & proto.ATTR_TIMESTAMP_TYPE)
    tstype = (proto.TSTYPE_LOG_APPEND_TIME if log_append
              else proto.TSTYPE_CREATE_TIME)
    base_ts = info.first_timestamp
    append_ts = info.max_timestamp
    base_off = info.base_offset
    not_persisted = MsgStatus.NOT_PERSISTED
    lazy = _materializer_lazy()
    if lazy is not None:
        # r5 hot path: FetchMessage with LAZY key/value (packed
        # buffer offsets; bytes created on first .value access) —
        # offset-commit-only consumers never pay the payload copy
        from ..client.msg import FetchMessage
        out, total, fixups = lazy(
            FetchMessage, records_bytes, fields.ctypes.data, n, topic,
            partition, base_off, fo, base_ts, append_ts,
            1 if log_append else 0, tstype, not_persisted)
        if fixups is not None:
            for idx, ho, nh in fixups:
                out[idx]._h = _parse_headers(records_bytes, ho, nh)
        return out, total
    mat = _materializer()
    if mat is not None:
        # bulk native materialization: tp_alloc + direct slot stores per
        # record instead of 18 bytecode attribute sets (enqlane.cpp)
        out, total, fixups = mat(
            Message, records_bytes, fields.ctypes.data, n, topic,
            partition, base_off, fo, base_ts, append_ts,
            1 if log_append else 0, tstype, not_persisted)
        if fixups is not None:
            for idx, ho, nh in fixups:
                out[idx].headers = _parse_headers(records_bytes, ho, nh)
        return out, total
    new = Message.__new__
    out = []
    append = out.append
    total = 0
    if not isinstance(records_bytes, bytes):
        records_bytes = bytes(records_bytes)   # keys/values sliced below
    for ts_d, off_d, ko, kl, vo, vl, ho, nh in fields.tolist():
        off = base_off + off_d
        if off < fo:
            continue
        m = new(Message)
        m.topic = topic
        m.partition = partition
        m.key = records_bytes[ko:ko + kl] if kl >= 0 else None
        m.value = records_bytes[vo:vo + vl] if vl >= 0 else None
        m.headers = _parse_headers(records_bytes, ho, nh) if nh else []
        m.offset = off
        m.timestamp = append_ts if log_append else base_ts + ts_d
        m.timestamp_type = tstype
        m.error = None
        m.opaque = None
        m.msgid = 0
        m.retries = 0
        m.status = not_persisted
        m.enq_time = 0.0
        m.ts_backoff = 0.0
        m.latency_us = 0
        m.on_delivery = None
        sz = (vl if vl > 0 else 0) + (kl if kl > 0 else 0)
        m.size = sz
        total += sz
        append(m)
    return out, total


_MAT = None
_MAT_ERR = False
_LAZY = None
_LAZY_ERR = False


def _materializer_lazy():
    """tk_enqlane.materialize_v2_lazy, or None when unavailable."""
    global _LAZY, _LAZY_ERR
    if _LAZY is None and not _LAZY_ERR:
        try:
            from ..client.arena import _mod
            m = _mod()
            _LAZY = getattr(m, "materialize_v2_lazy", None) if m else None
            if _LAZY is None:
                _LAZY_ERR = True
        except Exception:
            _LAZY_ERR = True
    return _LAZY


def _materializer():
    """tk_enqlane.materialize_v2, or None when the extension is
    unavailable (pure-Python fallback below stays authoritative)."""
    global _MAT, _MAT_ERR
    if _MAT is None and not _MAT_ERR:
        try:
            from ..client.arena import _mod
            m = _mod()
            _MAT = getattr(m, "materialize_v2", None) if m else None
            if _MAT is None:
                _MAT_ERR = True
        except Exception:
            _MAT_ERR = True
    return _MAT


def _parse_headers(buf: bytes, off: int, nh: int) -> list:
    sl = Slice(buf)
    sl.skip(off)
    return _read_headers(sl, nh)


def _read_headers(sl: "Slice", nh: int) -> list:
    headers = []
    for _ in range(nh):
        hklen = sl.read_varint()
        hk = sl.read(hklen).decode("utf-8", "replace")
        hvlen = sl.read_varint()
        hv = None if hvlen < 0 else sl.read(hvlen)
        headers.append((hk, hv))
    return headers


def _parse_records_v2_py(info: BatchInfo,
                         records_bytes: bytes) -> list[Record]:
    sl = Slice(records_bytes)
    log_append = bool(info.attrs & proto.ATTR_TIMESTAMP_TYPE)
    tstype = (proto.TSTYPE_LOG_APPEND_TIME if log_append
              else proto.TSTYPE_CREATE_TIME)
    out = []
    for _ in range(info.record_count):
        rec_len = sl.read_varint()
        rsl = sl.narrow(rec_len)
        rsl.read_i8()                       # record attributes
        ts_delta = rsl.read_varint()
        off_delta = rsl.read_varint()
        klen = rsl.read_varint()
        key = None if klen < 0 else rsl.read(klen)
        vlen = rsl.read_varint()
        value = None if vlen < 0 else rsl.read(vlen)
        nh = rsl.read_varint()
        headers = _read_headers(rsl, nh) if nh else []
        out.append(Record(
            key=key, value=value, headers=headers,
            timestamp=(info.max_timestamp if log_append
                       else info.first_timestamp + ts_delta),
            offset=info.base_offset + off_delta, msgver=2,
            is_control=info.is_control,
            is_transactional=info.is_transactional,
            producer_id=info.producer_id, timestamp_type=tstype))
    return out


def iter_batches(data):
    """Yield (BatchInfo, records_payload, full_batch) for each complete
    batch in a Fetch-response records blob. Brokers may return a partial
    batch at the tail — it is skipped (reference reader behavior).

    payload/full come back as memoryviews into ``data`` (no per-batch
    copy); every downstream consumer — the batched CRC verify, the
    native decompress, the record walk/materializer — reads them via
    the buffer protocol.  Callers that need owned bytes wrap with
    ``bytes(...)``."""
    mv = data if isinstance(data, memoryview) else memoryview(data)
    sl = Slice(mv)
    while sl.remains() >= proto.V2_HEADER_SIZE:
        start = sl.offset
        try:
            info = read_batch_header(sl)
        except Exception:
            return
        batch_total = proto.V2_OF_Length + 4 + info.length
        payload_len = batch_total - proto.V2_HEADER_SIZE
        if payload_len < 0 or sl.remains() < payload_len:
            return  # partial batch at tail
        payload = sl.view(payload_len)
        yield info, payload, mv[start:start + batch_total]


def verify_crc_v2(info: BatchInfo, full_batch: bytes) -> bool:
    """CRC32C over [Attributes..end] must equal the stored CRC."""
    return crc32c(full_batch[proto.V2_OF_Attributes:]) == info.crc


# ================================================================= v0/v1 ==
# Legacy MessageSet: [Offset i64][MessageSize i32][Crc u32(zlib)][Magic i8]
# [Attributes i8][Timestamp i64 (v1 only)][Key bytes][Value bytes].
# Compression wraps an inner MessageSet in a single wrapper message.
# (reference: rdkafka_msgset_writer.c MsgVersion<2 paths, reader :530-720)

def write_message_v01(buf: SegBuf, *, offset: int, magic: int, attrs: int,
                      timestamp: int, key: Optional[bytes],
                      value: Optional[bytes]) -> None:
    buf.write_i64(offset)
    size_pos = buf.write_i32(0)
    crc_pos = buf.write_u32(0)
    crc_start = buf.write_i8(magic)
    buf.write_i8(attrs)
    if magic == 1:
        buf.write_i64(timestamp)
    for b in (key, value):
        if b is None:
            buf.write_i32(-1)
        else:
            buf.write_i32(len(b))
            buf.write(b)
    end = len(buf)
    buf.update_i32(size_pos, end - (size_pos + 4))
    buf.update_u32(crc_pos, crc32(buf.as_bytes(crc_start, end)))


def write_msgset_v01(msgs: Iterable[Record], *, magic: int, codec: Optional[str],
                     now_ms: int, compress_fn=None,
                     base_offset: int = 0) -> bytes:
    inner = SegBuf()
    n = 0
    compressed = codec not in (None, "none") and compress_fn is not None
    for i, m in enumerate(msgs):
        ts = m.timestamp if m.timestamp and m.timestamp > 0 else now_ms
        # v1 compression wrappers carry *relative* inner offsets 0..n-1;
        # the wrapper offset is the absolute offset of the LAST message
        # (reference reader fixup at rdkafka_msgset_reader.c:666).
        off = i if (compressed and magic == 1) else base_offset + i
        write_message_v01(inner, offset=off, magic=magic, attrs=0,
                          timestamp=ts, key=m.key, value=m.value)
        n += 1
    raw = inner.as_bytes()
    if not codec or codec == "none" or compress_fn is None:
        return raw
    comp = compress_fn(raw)
    wrapper = SegBuf()
    # wrapper offset: v1 uses last inner offset (relative-offset era), v0 uses 0
    woffset = (base_offset + n - 1) if magic == 1 else base_offset
    write_message_v01(wrapper, offset=woffset, magic=magic,
                      attrs=CODEC_IDS[codec], timestamp=now_ms, key=None,
                      value=comp)
    return wrapper.as_bytes()


def split_msgset_segments(data) -> list[tuple[str, bytes]]:
    """Split a fetch records blob into maximal same-format runs —
    ("legacy", bytes) for v0/v1 messagesets, ("v2", bytes) for
    RecordBatches — preserving order. Logs written across a 0.11
    upgrade hold both; the reference reader dispatches per MessageSet
    from each header's MsgVersion (rdkafka_msgset_reader.c:1410).
    Both formats share the [i64 offset][i32 size] frame prefix with the
    magic byte at offset 16, so one uniform walk discriminates.
    A partial trailing frame is dropped (broker may truncate)."""
    segs: list[tuple[str, bytes]] = []
    off, n = 0, len(data)
    start = 0
    cur: Optional[str] = None
    while n - off >= 17:
        size = int.from_bytes(data[off + 8:off + 12], "big", signed=True)
        if size < 5 or off + 12 + size > n:
            break                       # partial/garbled tail
        kind = "v2" if data[off + 16] == 2 else "legacy"
        if cur is None:
            cur = kind
        elif kind != cur:
            segs.append((cur, bytes(data[start:off])))
            start, cur = off, kind
        off += 12 + size
    if cur is not None and off > start:
        if start == 0 and off == n:
            # single same-format run covering the whole blob (the
            # common case): hand back the caller's object uncopied —
            # it may be a memoryview into the response frame
            segs.append((cur, data))
        else:
            segs.append((cur, bytes(data[start:off])))
    return segs


def iter_legacy_crc_regions(data) -> list[tuple[int, int, bytes]]:
    """[(offset, stored_crc, crc_region)] for each top-level message of
    a legacy v0/v1 MessageSet. The per-message CRC (zlib polynomial,
    reference src/rdcrc32.c) covers [Magic..end-of-message]; for a
    compression wrapper that region includes the compressed payload, so
    verifying top-level frames checks the whole wire blob. Partial
    trailing messages are skipped (reference reader behavior)."""
    out = []
    data = bytes(data)
    sl = Slice(data)
    while sl.remains() >= 12:
        offset = sl.read_i64()
        size = sl.read_i32()
        if size < 4 or sl.remains() < size:
            break
        start = sl.offset
        crc = sl.read_u32()
        out.append((offset, crc, data[start + 4:start + size]))
        sl.skip(size - 4)
    return out


def parse_msgset_v01(data: bytes, decompress_fn=None) -> list[Record]:
    """Parse a legacy MessageSet, recursing into compression wrappers."""
    out: list[Record] = []
    sl = Slice(data)
    while sl.remains() >= 12:
        offset = sl.read_i64()
        size = sl.read_i32()
        if sl.remains() < size:
            break  # partial trailing message
        msl = sl.narrow(size)
        msl.read_u32()  # crc (verified optionally at a higher layer)
        magic = msl.read_i8()
        attrs = msl.read_i8()
        ts = -1
        if magic >= 1:
            ts = msl.read_i64()
        klen = msl.read_i32()
        key = None if klen < 0 else msl.read(klen)
        vlen = msl.read_i32()
        value = None if vlen < 0 else msl.read(vlen)
        codec = CODEC_NAMES.get(attrs & ATTR_CODEC_MASK)
        if codec and value is not None:
            if decompress_fn is None:
                raise ValueError(f"compressed ({codec}) legacy messageset "
                                 "but no decompressor supplied")
            inner = parse_msgset_v01(decompress_fn(codec, value),
                                     decompress_fn)
            if magic == 1 and inner:
                # v1 wrapper carries absolute offset of LAST inner message;
                # inner offsets are 0..n-1 relative (reference reader :666)
                base = offset - (len(inner) - 1)
                for r in inner:
                    r.offset += base
            out.extend(inner)
        else:
            out.append(Record(key=key, value=value, timestamp=ts,
                              offset=offset, msgver=magic))
    return out
