"""librdkafka_tpu.protocol"""
