"""Per-API request/response schemas.

One (request, response) Schema pair per Kafka API at the protocol version
this client speaks — the declarative equivalent of the reference's
rd_kafka_XxxRequest() builders + rd_kafka_handle_Xxx() parsers
(src/rdkafka_request.c, 3893 LoC). Both the client and the mock broker
(mock/cluster.py) use these same schemas, making the mock a protocol
oracle: bytes built here must parse there and vice versa.

Versions follow what librdkafka v1.3.0 negotiates for a modern (2.x)
broker: Produce v3 / Fetch v4 (MsgVer2 + read_committed), ApiVersions v0,
JoinGroup v2 (rebalance_timeout), etc.
"""
from __future__ import annotations

from .proto import ApiKey
from .types import (Array, Boolean, Bytes, Int8, Int16, Int32, Int64,
                    NullableString, Schema, String)

# ------------------------------------------------------------- headers ----
REQUEST_HEADER = Schema(
    ("api_key", Int16), ("api_version", Int16),
    ("correlation_id", Int32), ("client_id", NullableString))
RESPONSE_HEADER = Schema(("correlation_id", Int32))

# ---------------------------------------------------------- ApiVersions ---
APIVERSIONS_V0_REQ = Schema()
APIVERSIONS_V0_RESP = Schema(
    ("error_code", Int16),
    ("api_versions", Array(Schema(
        ("api_key", Int16), ("min_version", Int16), ("max_version", Int16)))))

# -------------------------------------------------------------- Metadata --
METADATA_V2_REQ = Schema(("topics", Array(String)))  # null array = all topics
# v4 (KIP-204): producer metadata may auto-create, consumer only when
# allow.auto.create.topics (reference: rd_kafka_MetadataRequest's
# allow_auto_topic_creation flag, rdkafka_request.c)
METADATA_V4_REQ = Schema(("topics", Array(String)),
                         ("allow_auto_topic_creation", Boolean),
                         defaults={"allow_auto_topic_creation": True})
METADATA_V2_RESP = Schema(
    ("brokers", Array(Schema(
        ("node_id", Int32), ("host", String), ("port", Int32),
        ("rack", NullableString)))),
    ("cluster_id", NullableString),
    ("controller_id", Int32),
    ("topics", Array(Schema(
        ("error_code", Int16), ("topic", String), ("is_internal", Boolean),
        ("partitions", Array(Schema(
            ("error_code", Int16), ("partition", Int32), ("leader", Int32),
            ("replicas", Array(Int32)), ("isr", Array(Int32)))))))))
METADATA_V3_RESP = Schema(("throttle_time_ms", Int32),
                          *METADATA_V2_RESP.fields)
METADATA_V4_RESP = METADATA_V3_RESP       # v4 only adds the request flag

# --------------------------------------------------------------- Produce --
# Legacy versions for pre-0.11 brokers (broker.version.fallback;
# reference emits the version the feature set allows,
# rdkafka_request.c:2927 + rdkafka_feature.c)
PRODUCE_V0_REQ = Schema(
    ("acks", Int16), ("timeout", Int32),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("records", Bytes))))))))
PRODUCE_V0_RESP = Schema(
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16),
            ("base_offset", Int64))))))))
# v2: throttle + per-partition log_append_time, req still w/o txn id
PRODUCE_V2_REQ = PRODUCE_V0_REQ
PRODUCE_V2_RESP = Schema(
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16),
            ("base_offset", Int64), ("log_append_time", Int64))))))),
    ("throttle_time_ms", Int32))

PRODUCE_V3_REQ = Schema(
    ("transactional_id", NullableString),
    ("acks", Int16), ("timeout", Int32),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("records", Bytes))))))))
PRODUCE_V3_RESP = Schema(
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16),
            ("base_offset", Int64), ("log_append_time", Int64))))))),
    ("throttle_time_ms", Int32))

# ----------------------------------------------------------------- Fetch --
FETCH_V0_REQ = Schema(
    ("replica_id", Int32), ("max_wait_time", Int32), ("min_bytes", Int32),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("fetch_offset", Int64),
            ("max_bytes", Int32))))))))
FETCH_V0_RESP = Schema(
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16),
            ("high_watermark", Int64), ("records", Bytes))))))))
FETCH_V2_REQ = FETCH_V0_REQ
FETCH_V2_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16),
            ("high_watermark", Int64), ("records", Bytes))))))))

FETCH_V4_REQ = Schema(
    ("replica_id", Int32), ("max_wait_time", Int32), ("min_bytes", Int32),
    ("max_bytes", Int32), ("isolation_level", Int8),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("fetch_offset", Int64),
            ("max_bytes", Int32))))))))
FETCH_V4_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16),
            ("high_watermark", Int64), ("last_stable_offset", Int64),
            ("aborted_transactions", Array(Schema(
                ("producer_id", Int64), ("first_offset", Int64)))),
            ("records", Bytes))))))))

# Fetch v5-v11 evolution (KIP-227 sessions, KIP-392 follower fetching —
# reference: rd_kafka_FetchRequest versioning in rdkafka_broker.c:3791+).
# Schema `defaults` keep version-agnostic request bodies working: a
# body WITHOUT session keys serializes as a sessionless full fetch
# (session_id=0, epoch=-1), the reference's only shape.  With
# fetch.session.enable (default) the client goes beyond the reference:
# client/fetch_session.py negotiates per-broker KIP-227 sessions and
# fills session_id/session_epoch/forgotten_topics explicitly
# (Broker._consumer_serve); the mock broker's session cache is the
# other end (mock/cluster.py _h_Fetch).
_FETCH_PART_V5 = Schema(
    ("partition", Int32), ("fetch_offset", Int64),
    ("log_start_offset", Int64), ("max_bytes", Int32),
    defaults={"log_start_offset": -1})
_FETCH_PART_V9 = Schema(
    ("partition", Int32), ("current_leader_epoch", Int32),
    ("fetch_offset", Int64), ("log_start_offset", Int64),
    ("max_bytes", Int32),
    defaults={"current_leader_epoch": -1, "log_start_offset": -1})
_FORGOTTEN = ("forgotten_topics", Array(Schema(
    ("topic", String), ("partitions", Array(Int32)))))


def _fetch_req(part_schema, *, session: bool, rack: bool) -> Schema:
    fields = [("replica_id", Int32), ("max_wait_time", Int32),
              ("min_bytes", Int32), ("max_bytes", Int32),
              ("isolation_level", Int8)]
    defaults = {}
    if session:
        fields += [("session_id", Int32), ("session_epoch", Int32)]
        defaults.update(session_id=0, session_epoch=-1)
    fields.append(("topics", Array(Schema(
        ("topic", String), ("partitions", Array(part_schema))))))
    if session:
        fields.append(_FORGOTTEN)
        defaults["forgotten_topics"] = []
    if rack:
        fields.append(("rack_id", String))
        defaults["rack_id"] = ""
    return Schema(*fields, defaults=defaults)


def _fetch_resp(*, session: bool, preferred: bool) -> Schema:
    part_fields = [("partition", Int32), ("error_code", Int16),
                   ("high_watermark", Int64), ("last_stable_offset", Int64),
                   ("log_start_offset", Int64),
                   ("aborted_transactions", Array(Schema(
                       ("producer_id", Int64), ("first_offset", Int64))))]
    pdef = {"log_start_offset": -1}
    if preferred:
        part_fields.append(("preferred_read_replica", Int32))
        pdef["preferred_read_replica"] = -1
    part_fields.append(("records", Bytes))
    fields = [("throttle_time_ms", Int32)]
    defaults = {}
    if session:
        fields += [("error_code", Int16), ("session_id", Int32)]
        defaults.update(error_code=0, session_id=0)
    fields.append(("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(*part_fields, defaults=pdef)))))))
    return Schema(*fields, defaults=defaults)


FETCH_V5_REQ = _fetch_req(_FETCH_PART_V5, session=False, rack=False)
FETCH_V5_RESP = _fetch_resp(session=False, preferred=False)
FETCH_V7_REQ = _fetch_req(_FETCH_PART_V5, session=True, rack=False)
FETCH_V7_RESP = _fetch_resp(session=True, preferred=False)
FETCH_V9_REQ = _fetch_req(_FETCH_PART_V9, session=True, rack=False)
FETCH_V11_REQ = _fetch_req(_FETCH_PART_V9, session=True, rack=True)
FETCH_V11_RESP = _fetch_resp(session=True, preferred=True)

# ----------------------------------------------------------- ListOffsets --
LISTOFFSETS_V1_REQ = Schema(
    ("replica_id", Int32),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("timestamp", Int64))))))))
LISTOFFSETS_V1_RESP = Schema(
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16),
            ("timestamp", Int64), ("offset", Int64))))))))

# ------------------------------------------------------- FindCoordinator --
FINDCOORDINATOR_V1_REQ = Schema(("key", String), ("key_type", Int8))
FINDCOORDINATOR_V1_RESP = Schema(
    ("throttle_time_ms", Int32), ("error_code", Int16),
    ("error_message", NullableString),
    ("node_id", Int32), ("host", String), ("port", Int32))

# ------------------------------------------------------------- JoinGroup --
JOINGROUP_V2_REQ = Schema(
    ("group_id", String), ("session_timeout", Int32),
    ("rebalance_timeout", Int32), ("member_id", String),
    ("protocol_type", String),
    ("protocols", Array(Schema(("name", String), ("metadata", Bytes)))))
JOINGROUP_V2_RESP = Schema(
    ("throttle_time_ms", Int32), ("error_code", Int16),
    ("generation_id", Int32), ("protocol", String),
    ("leader_id", String), ("member_id", String),
    ("members", Array(Schema(("member_id", String), ("metadata", Bytes)))))

# JoinGroup v5 (KIP-345 static membership): + group_instance_id
JOINGROUP_V5_REQ = Schema(
    ("group_id", String), ("session_timeout", Int32),
    ("rebalance_timeout", Int32), ("member_id", String),
    ("group_instance_id", NullableString),
    ("protocol_type", String),
    ("protocols", Array(Schema(("name", String), ("metadata", Bytes)))))
JOINGROUP_V5_RESP = Schema(
    ("throttle_time_ms", Int32), ("error_code", Int16),
    ("generation_id", Int32), ("protocol", String),
    ("leader_id", String), ("member_id", String),
    ("members", Array(Schema(
        ("member_id", String), ("group_instance_id", NullableString),
        ("metadata", Bytes)))))

# ------------------------------------------------------------- SyncGroup --
SYNCGROUP_V1_REQ = Schema(
    ("group_id", String), ("generation_id", Int32), ("member_id", String),
    ("assignments", Array(Schema(
        ("member_id", String), ("assignment", Bytes)))))
SYNCGROUP_V1_RESP = Schema(
    ("throttle_time_ms", Int32), ("error_code", Int16),
    ("assignment", Bytes))

# ------------------------------------------------------------- Heartbeat --
HEARTBEAT_V1_REQ = Schema(
    ("group_id", String), ("generation_id", Int32), ("member_id", String))
HEARTBEAT_V1_RESP = Schema(("throttle_time_ms", Int32), ("error_code", Int16))

# ------------------------------------------------------------ LeaveGroup --
LEAVEGROUP_V1_REQ = Schema(("group_id", String), ("member_id", String))
LEAVEGROUP_V1_RESP = Schema(("throttle_time_ms", Int32), ("error_code", Int16))

# ----------------------------------------------------------- OffsetCommit --
OFFSETCOMMIT_V2_REQ = Schema(
    ("group_id", String), ("generation_id", Int32), ("member_id", String),
    ("retention_time", Int64),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("offset", Int64),
            ("metadata", NullableString))))))))
OFFSETCOMMIT_V2_RESP = Schema(
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16))))))))

# ------------------------------------------------------------ OffsetFetch --
OFFSETFETCH_V1_REQ = Schema(
    ("group_id", String),
    ("topics", Array(Schema(
        ("topic", String), ("partitions", Array(Int32))))))
OFFSETFETCH_V1_RESP = Schema(
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("offset", Int64),
            ("metadata", NullableString), ("error_code", Int16))))))))

# ---------------------------------------------------------- SaslHandshake --
SASLHANDSHAKE_V1_REQ = Schema(("mechanism", String))
SASLHANDSHAKE_V1_RESP = Schema(
    ("error_code", Int16), ("mechanisms", Array(String)))

# ------------------------------------------------------- SaslAuthenticate --
SASLAUTHENTICATE_V0_REQ = Schema(("auth_bytes", Bytes))
SASLAUTHENTICATE_V0_RESP = Schema(
    ("error_code", Int16), ("error_message", NullableString),
    ("auth_bytes", Bytes))

# --------------------------------------------------------- InitProducerId --
INITPRODUCERID_V1_REQ = Schema(
    ("transactional_id", NullableString), ("transaction_timeout_ms", Int32))
INITPRODUCERID_V1_RESP = Schema(
    ("throttle_time_ms", Int32), ("error_code", Int16),
    ("producer_id", Int64), ("producer_epoch", Int16))

# ----------------------------------------------------- AddPartitionsToTxn --
# (KIP-98 transactional producer; reference: the rd_kafka_txn_* request
# builders land in librdkafka 1.4 — this client implements the same
# v0 wire schemas the 2.x brokers of its era negotiate)
ADDPARTITIONSTOTXN_V0_REQ = Schema(
    ("transactional_id", String), ("producer_id", Int64),
    ("producer_epoch", Int16),
    ("topics", Array(Schema(
        ("topic", String), ("partitions", Array(Int32))))))
ADDPARTITIONSTOTXN_V0_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("results", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16))))))))

# ------------------------------------------------------- AddOffsetsToTxn --
ADDOFFSETSTOTXN_V0_REQ = Schema(
    ("transactional_id", String), ("producer_id", Int64),
    ("producer_epoch", Int16), ("group_id", String))
ADDOFFSETSTOTXN_V0_RESP = Schema(
    ("throttle_time_ms", Int32), ("error_code", Int16))

# ---------------------------------------------------------------- EndTxn --
ENDTXN_V1_REQ = Schema(
    ("transactional_id", String), ("producer_id", Int64),
    ("producer_epoch", Int16), ("committed", Boolean))
ENDTXN_V1_RESP = Schema(
    ("throttle_time_ms", Int32), ("error_code", Int16))

# ------------------------------------------------------- TxnOffsetCommit --
TXNOFFSETCOMMIT_V0_REQ = Schema(
    ("transactional_id", String), ("group_id", String),
    ("producer_id", Int64), ("producer_epoch", Int16),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("offset", Int64),
            ("metadata", NullableString))))))))
TXNOFFSETCOMMIT_V0_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16))))))))

# ----------------------------------------------------------- CreateTopics --
CREATETOPICS_V2_REQ = Schema(
    ("topics", Array(Schema(
        ("topic", String), ("num_partitions", Int32),
        ("replication_factor", Int16),
        ("replica_assignment", Array(Schema(
            ("partition", Int32), ("replicas", Array(Int32))))),
        ("configs", Array(Schema(
            ("name", String), ("value", NullableString))))))),
    ("timeout", Int32), ("validate_only", Boolean))
CREATETOPICS_V2_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("topics", Array(Schema(
        ("topic", String), ("error_code", Int16),
        ("error_message", NullableString)))))

# ----------------------------------------------------------- DeleteTopics --
DELETETOPICS_V1_REQ = Schema(("topics", Array(String)), ("timeout", Int32))
DELETETOPICS_V1_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("topics", Array(Schema(("topic", String), ("error_code", Int16)))))

# ------------------------------------------------------- CreatePartitions --
CREATEPARTITIONS_V1_REQ = Schema(
    ("topics", Array(Schema(
        ("topic", String), ("count", Int32),
        ("assignment", Array(Schema(("broker_ids", Array(Int32)))))))),
    ("timeout", Int32), ("validate_only", Boolean))
CREATEPARTITIONS_V1_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("topics", Array(Schema(
        ("topic", String), ("error_code", Int16),
        ("error_message", NullableString)))))

# -------------------------------------------------------- DescribeConfigs --
DESCRIBECONFIGS_V1_REQ = Schema(
    ("resources", Array(Schema(
        ("resource_type", Int8), ("resource_name", String),
        ("config_names", Array(String))))),
    ("include_synonyms", Boolean))
DESCRIBECONFIGS_V1_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("resources", Array(Schema(
        ("error_code", Int16), ("error_message", NullableString),
        ("resource_type", Int8), ("resource_name", String),
        ("entries", Array(Schema(
            ("name", String), ("value", NullableString),
            ("read_only", Boolean), ("source", Int8),
            ("sensitive", Boolean),
            ("synonyms", Array(Schema(
                ("name", String), ("value", NullableString),
                ("source", Int8)))))))))))

# ----------------------------------------------------------- AlterConfigs --
ALTERCONFIGS_V0_REQ = Schema(
    ("resources", Array(Schema(
        ("resource_type", Int8), ("resource_name", String),
        ("entries", Array(Schema(
            ("name", String), ("value", NullableString))))))),
    ("validate_only", Boolean))
ALTERCONFIGS_V0_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("resources", Array(Schema(
        ("error_code", Int16), ("error_message", NullableString),
        ("resource_type", Int8), ("resource_name", String)))))

# --------------------------------------------------------- DescribeGroups --
DESCRIBEGROUPS_V0_REQ = Schema(("groups", Array(String)))
DESCRIBEGROUPS_V0_RESP = Schema(
    ("groups", Array(Schema(
        ("error_code", Int16), ("group_id", String), ("state", String),
        ("protocol_type", String), ("protocol", String),
        ("members", Array(Schema(
            ("member_id", String), ("client_id", String),
            ("client_host", String), ("metadata", Bytes),
            ("assignment", Bytes))))))))

# ------------------------------------------------------------- ListGroups --
LISTGROUPS_V0_REQ = Schema()
LISTGROUPS_V0_RESP = Schema(
    ("error_code", Int16),
    ("groups", Array(Schema(
        ("group_id", String), ("protocol_type", String)))))

# ----------------------------------------------------------- DeleteGroups --
DELETEGROUPS_V0_REQ = Schema(("groups", Array(String)))
DELETEGROUPS_V0_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("results", Array(Schema(("group_id", String), ("error_code", Int16)))))


#: {ApiKey: (version, request_schema, response_schema)} — the single version
#: this client emits per API (negotiation picks min(ours, broker's)).
APIS: dict[ApiKey, tuple[int, Schema, Schema]] = {
    ApiKey.ApiVersions: (0, APIVERSIONS_V0_REQ, APIVERSIONS_V0_RESP),
    ApiKey.Metadata: (4, METADATA_V4_REQ, METADATA_V4_RESP),
    ApiKey.Produce: (3, PRODUCE_V3_REQ, PRODUCE_V3_RESP),
    ApiKey.Fetch: (11, FETCH_V11_REQ, FETCH_V11_RESP),
    ApiKey.ListOffsets: (1, LISTOFFSETS_V1_REQ, LISTOFFSETS_V1_RESP),
    ApiKey.FindCoordinator: (1, FINDCOORDINATOR_V1_REQ, FINDCOORDINATOR_V1_RESP),
    ApiKey.JoinGroup: (5, JOINGROUP_V5_REQ, JOINGROUP_V5_RESP),
    ApiKey.SyncGroup: (1, SYNCGROUP_V1_REQ, SYNCGROUP_V1_RESP),
    ApiKey.Heartbeat: (1, HEARTBEAT_V1_REQ, HEARTBEAT_V1_RESP),
    ApiKey.LeaveGroup: (1, LEAVEGROUP_V1_REQ, LEAVEGROUP_V1_RESP),
    ApiKey.OffsetCommit: (2, OFFSETCOMMIT_V2_REQ, OFFSETCOMMIT_V2_RESP),
    ApiKey.OffsetFetch: (1, OFFSETFETCH_V1_REQ, OFFSETFETCH_V1_RESP),
    ApiKey.SaslHandshake: (1, SASLHANDSHAKE_V1_REQ, SASLHANDSHAKE_V1_RESP),
    ApiKey.SaslAuthenticate: (0, SASLAUTHENTICATE_V0_REQ, SASLAUTHENTICATE_V0_RESP),
    ApiKey.InitProducerId: (1, INITPRODUCERID_V1_REQ, INITPRODUCERID_V1_RESP),
    ApiKey.AddPartitionsToTxn: (0, ADDPARTITIONSTOTXN_V0_REQ,
                                ADDPARTITIONSTOTXN_V0_RESP),
    ApiKey.AddOffsetsToTxn: (0, ADDOFFSETSTOTXN_V0_REQ,
                             ADDOFFSETSTOTXN_V0_RESP),
    ApiKey.EndTxn: (1, ENDTXN_V1_REQ, ENDTXN_V1_RESP),
    ApiKey.TxnOffsetCommit: (0, TXNOFFSETCOMMIT_V0_REQ,
                             TXNOFFSETCOMMIT_V0_RESP),
    ApiKey.CreateTopics: (2, CREATETOPICS_V2_REQ, CREATETOPICS_V2_RESP),
    ApiKey.DeleteTopics: (1, DELETETOPICS_V1_REQ, DELETETOPICS_V1_RESP),
    ApiKey.CreatePartitions: (1, CREATEPARTITIONS_V1_REQ, CREATEPARTITIONS_V1_RESP),
    ApiKey.DescribeConfigs: (1, DESCRIBECONFIGS_V1_REQ, DESCRIBECONFIGS_V1_RESP),
    ApiKey.AlterConfigs: (0, ALTERCONFIGS_V0_REQ, ALTERCONFIGS_V0_RESP),
    ApiKey.DescribeGroups: (0, DESCRIBEGROUPS_V0_REQ, DESCRIBEGROUPS_V0_RESP),
    ApiKey.ListGroups: (0, LISTGROUPS_V0_REQ, LISTGROUPS_V0_RESP),
    ApiKey.DeleteGroups: (0, DELETEGROUPS_V0_REQ, DELETEGROUPS_V0_RESP),
}


#: Explicit (api, version) schema overrides for legacy broker support
#: (broker.version.fallback; reference rdkafka_feature.c maps version
#: ranges to emitted request versions). Versions between table entries
#: resolve DOWN to the nearest listed one.
PRODUCE_V1_RESP = Schema(     # v1: +throttle, no log_append_time yet
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16),
            ("base_offset", Int64))))))),
    ("throttle_time_ms", Int32))

VERSIONED: dict[tuple[ApiKey, int], tuple[Schema, Schema]] = {
    (ApiKey.Produce, 0): (PRODUCE_V0_REQ, PRODUCE_V0_RESP),
    (ApiKey.Produce, 1): (PRODUCE_V0_REQ, PRODUCE_V1_RESP),
    (ApiKey.Produce, 2): (PRODUCE_V2_REQ, PRODUCE_V2_RESP),
    (ApiKey.Fetch, 0): (FETCH_V0_REQ, FETCH_V0_RESP),
    (ApiKey.Fetch, 1): (FETCH_V2_REQ, FETCH_V2_RESP),
    (ApiKey.Fetch, 2): (FETCH_V2_REQ, FETCH_V2_RESP),
    (ApiKey.Fetch, 3): (FETCH_V2_REQ, FETCH_V2_RESP),
}
# Fetch v3 request adds top-level max_bytes (response like v2)
FETCH_V3_REQ = Schema(
    ("replica_id", Int32), ("max_wait_time", Int32), ("min_bytes", Int32),
    ("max_bytes", Int32),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("fetch_offset", Int64),
            ("max_bytes", Int32))))))))
VERSIONED[(ApiKey.Fetch, 3)] = (FETCH_V3_REQ, FETCH_V2_RESP)
VERSIONED[(ApiKey.Fetch, 4)] = (FETCH_V4_REQ, FETCH_V4_RESP)
VERSIONED[(ApiKey.Fetch, 5)] = (FETCH_V5_REQ, FETCH_V5_RESP)
VERSIONED[(ApiKey.Fetch, 6)] = (FETCH_V5_REQ, FETCH_V5_RESP)
VERSIONED[(ApiKey.Fetch, 7)] = (FETCH_V7_REQ, FETCH_V7_RESP)
VERSIONED[(ApiKey.Fetch, 8)] = (FETCH_V7_REQ, FETCH_V7_RESP)
VERSIONED[(ApiKey.Fetch, 9)] = (FETCH_V9_REQ, FETCH_V7_RESP)
VERSIONED[(ApiKey.Fetch, 10)] = (FETCH_V9_REQ, FETCH_V7_RESP)

# --- group / offset APIs for pre-1.0 brokers (all subset schemas: the
# client builds one superset body dict; a version's schema writes only
# its own fields) ---
JOINGROUP_V0_REQ = Schema(
    ("group_id", String), ("session_timeout", Int32), ("member_id", String),
    ("protocol_type", String),
    ("protocols", Array(Schema(("name", String), ("metadata", Bytes)))))
JOINGROUP_V01_RESP = Schema(
    ("error_code", Int16),
    ("generation_id", Int32), ("protocol", String),
    ("leader_id", String), ("member_id", String),
    ("members", Array(Schema(("member_id", String), ("metadata", Bytes)))))
VERSIONED[(ApiKey.JoinGroup, 0)] = (JOINGROUP_V0_REQ, JOINGROUP_V01_RESP)
VERSIONED[(ApiKey.JoinGroup, 1)] = (JOINGROUP_V2_REQ, JOINGROUP_V01_RESP)
for _jv in (2, 3, 4):
    VERSIONED[(ApiKey.JoinGroup, _jv)] = (JOINGROUP_V2_REQ,
                                          JOINGROUP_V2_RESP)

SYNCGROUP_V0_RESP = Schema(("error_code", Int16), ("assignment", Bytes))
VERSIONED[(ApiKey.SyncGroup, 0)] = (SYNCGROUP_V1_REQ, SYNCGROUP_V0_RESP)

HEARTBEAT_V0_RESP = Schema(("error_code", Int16))
VERSIONED[(ApiKey.Heartbeat, 0)] = (HEARTBEAT_V1_REQ, HEARTBEAT_V0_RESP)
VERSIONED[(ApiKey.LeaveGroup, 0)] = (LEAVEGROUP_V1_REQ, HEARTBEAT_V0_RESP)

# FindCoordinator v0 ("GroupCoordinator"): bare group key, no throttle
FINDCOORDINATOR_V0_REQ = Schema(("key", String))
FINDCOORDINATOR_V0_RESP = Schema(
    ("error_code", Int16),
    ("node_id", Int32), ("host", String), ("port", Int32))
VERSIONED[(ApiKey.FindCoordinator, 0)] = (FINDCOORDINATOR_V0_REQ,
                                          FINDCOORDINATOR_V0_RESP)

# ListOffsets v0: per-partition max_num_offsets + plural offsets reply
LISTOFFSETS_V0_REQ = Schema(
    ("replica_id", Int32),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("timestamp", Int64),
            ("max_num_offsets", Int32))))))))
LISTOFFSETS_V0_RESP = Schema(
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("error_code", Int16),
            ("offsets", Array(Int64)))))))))
VERSIONED[(ApiKey.ListOffsets, 0)] = (LISTOFFSETS_V0_REQ,
                                      LISTOFFSETS_V0_RESP)

# Metadata v0: no rack/is_internal/cluster_id/controller_id; v1 adds
# rack + controller_id + is_internal (cluster_id arrives in v2)
METADATA_V0_RESP = Schema(
    ("brokers", Array(Schema(
        ("node_id", Int32), ("host", String), ("port", Int32)))),
    ("topics", Array(Schema(
        ("error_code", Int16), ("topic", String),
        ("partitions", Array(Schema(
            ("error_code", Int16), ("partition", Int32), ("leader", Int32),
            ("replicas", Array(Int32)), ("isr", Array(Int32)))))))))
METADATA_V1_RESP = Schema(
    ("brokers", Array(Schema(
        ("node_id", Int32), ("host", String), ("port", Int32),
        ("rack", NullableString)))),
    ("controller_id", Int32),
    ("topics", Array(Schema(
        ("error_code", Int16), ("topic", String), ("is_internal", Boolean),
        ("partitions", Array(Schema(
            ("error_code", Int16), ("partition", Int32), ("leader", Int32),
            ("replicas", Array(Int32)), ("isr", Array(Int32)))))))))
VERSIONED[(ApiKey.Metadata, 0)] = (METADATA_V2_REQ, METADATA_V0_RESP)
VERSIONED[(ApiKey.Metadata, 1)] = (METADATA_V2_REQ, METADATA_V1_RESP)
VERSIONED[(ApiKey.Metadata, 2)] = (METADATA_V2_REQ, METADATA_V2_RESP)
VERSIONED[(ApiKey.Metadata, 3)] = (METADATA_V2_REQ, METADATA_V3_RESP)

# OffsetCommit v0/v1 (pre-0.9 brokers)
OFFSETCOMMIT_V0_REQ = Schema(
    ("group_id", String),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("offset", Int64),
            ("metadata", NullableString))))))))
OFFSETCOMMIT_V1_REQ = Schema(
    ("group_id", String), ("generation_id", Int32), ("member_id", String),
    ("topics", Array(Schema(
        ("topic", String),
        ("partitions", Array(Schema(
            ("partition", Int32), ("offset", Int64),
            ("timestamp", Int64), ("metadata", NullableString))))))))
VERSIONED[(ApiKey.OffsetCommit, 0)] = (OFFSETCOMMIT_V0_REQ,
                                       OFFSETCOMMIT_V2_RESP)
VERSIONED[(ApiKey.OffsetCommit, 1)] = (OFFSETCOMMIT_V1_REQ,
                                       OFFSETCOMMIT_V2_RESP)

# CreateTopics v0/v1 and DeleteTopics v0: no throttle (v0 also lacks
# error_message / validate_only)
CREATETOPICS_V0_REQ = Schema(
    ("topics", Array(Schema(
        ("topic", String), ("num_partitions", Int32),
        ("replication_factor", Int16),
        ("replica_assignment", Array(Schema(
            ("partition", Int32), ("replicas", Array(Int32))))),
        ("configs", Array(Schema(
            ("name", String), ("value", NullableString))))))),
    ("timeout", Int32))
CREATETOPICS_V0_RESP = Schema(
    ("topics", Array(Schema(("topic", String), ("error_code", Int16)))))
CREATETOPICS_V1_RESP = Schema(
    ("topics", Array(Schema(
        ("topic", String), ("error_code", Int16),
        ("error_message", NullableString)))))
VERSIONED[(ApiKey.CreateTopics, 0)] = (CREATETOPICS_V0_REQ,
                                       CREATETOPICS_V0_RESP)
VERSIONED[(ApiKey.CreateTopics, 1)] = (CREATETOPICS_V2_REQ,
                                       CREATETOPICS_V1_RESP)
DELETETOPICS_V0_RESP = Schema(
    ("topics", Array(Schema(("topic", String), ("error_code", Int16)))))
VERSIONED[(ApiKey.DeleteTopics, 0)] = (DELETETOPICS_V1_REQ,
                                       DELETETOPICS_V0_RESP)

# DescribeConfigs v0: entries without synonyms, no include_synonyms
DESCRIBECONFIGS_V0_REQ = Schema(
    ("resources", Array(Schema(
        ("resource_type", Int8), ("resource_name", String),
        ("config_names", Array(String))))))
DESCRIBECONFIGS_V0_RESP = Schema(
    ("throttle_time_ms", Int32),
    ("resources", Array(Schema(
        ("error_code", Int16), ("error_message", NullableString),
        ("resource_type", Int8), ("resource_name", String),
        ("entries", Array(Schema(
            ("name", String), ("value", NullableString),
            ("read_only", Boolean), ("is_default", Boolean),
            ("sensitive", Boolean))))))))
VERSIONED[(ApiKey.DescribeConfigs, 0)] = (DESCRIBECONFIGS_V0_REQ,
                                          DESCRIBECONFIGS_V0_RESP)


def schemas_for(api: ApiKey, version: int | None) -> tuple[int, Schema, Schema]:
    """Resolve (version, req_schema, resp_schema): explicit versioned
    entry if present, else the default single-version schema."""
    ver, req_schema, resp_schema = APIS[api]
    if version is not None and version != ver:
        ovr = VERSIONED.get((api, version))
        if ovr is not None:
            return version, ovr[0], ovr[1]
        return version, req_schema, resp_schema
    return ver, req_schema, resp_schema


def build_request_buf(api: ApiKey, corrid: int, client_id: str | None,
                      body: dict, version: int | None = None):
    """Frame a request as a SegBuf: 4-byte size + header + body.  Large
    Bytes fields (RecordBatch wire) ride as spliced read-only segments,
    so the broker can hand the segments straight to sendmsg without
    flattening (reference: requests are rd_buf segment chains sent via
    iovec, rdkafka_buf.c + rdkafka_transport.c:109)."""
    from ..utils.buf import SegBuf
    ver, req_schema, _ = schemas_for(api, version)
    buf = SegBuf()
    szpos = buf.write_i32(0)
    REQUEST_HEADER.write(buf, {"api_key": int(api),
                               "api_version": ver,
                               "correlation_id": corrid,
                               "client_id": client_id})
    req_schema.write(buf, body)
    buf.update_i32(szpos, len(buf) - 4)
    return buf


def build_request(api: ApiKey, corrid: int, client_id: str | None,
                  body: dict, version: int | None = None) -> bytes:
    """Frame a request: 4-byte size + header + body (rd_kafka_buf pattern)."""
    return build_request_buf(api, corrid, client_id, body,
                             version=version).as_bytes()


def build_response(api: ApiKey, corrid: int, body: dict,
                   version: int | None = None) -> bytes:
    from ..utils.buf import SegBuf
    _, _, resp_schema = schemas_for(api, version)
    buf = SegBuf()
    szpos = buf.write_i32(0)
    buf.write_i32(corrid)
    resp_schema.write(buf, body)
    buf.update_i32(szpos, len(buf) - 4)
    return buf.as_bytes()


def parse_request(payload: bytes) -> tuple[dict, dict]:
    """Parse an unframed request (after the 4-byte size). Returns (header, body)."""
    from ..utils.buf import Slice
    sl = Slice(payload)
    hdr = REQUEST_HEADER.read(sl)
    api = ApiKey(hdr["api_key"])
    _, req_schema, _ = schemas_for(api, hdr["api_version"])
    return hdr, req_schema.read(sl)


def parse_response(api: ApiKey, payload: bytes,
                   version: int | None = None) -> tuple[int, dict]:
    """Parse an unframed response. Returns (correlation_id, body)."""
    from ..utils.buf import Slice
    sl = Slice(payload)
    corrid = sl.read_i32()
    _, _, resp_schema = schemas_for(api, version)
    return corrid, resp_schema.read(sl)
