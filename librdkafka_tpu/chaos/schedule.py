"""Fault-schedule engine: a declarative DSL of timed fault steps,
executed by a scheduler thread against the mock cluster's controller
surface (``kill_broker``/``restart_broker``/``set_partition_leader``)
and the sockem network-shaping shim.

The reference builds its robustness story on exactly this shape —
scripted network/broker faults driven by test scenarios (tests/sockem.c
interposition; 0075-retry.c latency scripts; 0093-holb.c per-connection
shaping) — but each test hand-rolls its own timing loop.  Here the
script is data::

    sched = (Schedule(seed=42)
             .at(0.5, broker_kill("any"))
             .at(1.1, broker_restart())             # revives in kill order
             .at(1.5, net(delay_ms=200, jitter_ms=50))
             .at(2.0, leader_migrate("payments", "any"))
             .at(2.5, conn_kill()))
    chaos = ChaosScheduler(cluster, sockem=em)
    chaos.start(sched)
    ...                                             # drive traffic
    chaos.join()
    chaos.timeline                                  # what actually fired

**Determinism contract** (the replay-from-seed workflow, CHAOS.md):
steps execute in (time, insertion-order) order and every random choice
("any" broker, "any" partition, jittered repeat times) draws from one
``random.Random(schedule.seed)`` consumed in that same order.  Cluster
state that feeds a choice (the alive-broker set, current leaders) is
itself only mutated by earlier steps, so the same seed resolves the
same targets no matter how wall-clock scheduling jitters: the
``replay_key()`` of two runs with one seed is identical, and a failing
storm replays exactly.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..obs import metrics as _metrics


# ------------------------------------------------------------- actions --
class Action:
    """One fault step's behavior: ``resolve`` draws targets (consuming
    the schedule's rng — the ONLY rng use, so replays are exact), then
    ``apply`` executes against cluster/sockem."""

    name = "action"

    def resolve(self, ctx: "ChaosContext", rng: random.Random) -> dict:
        return {}

    def apply(self, ctx: "ChaosContext", resolved: dict) -> None:
        raise NotImplementedError

    def __repr__(self):
        return f"<{self.name}>"


class _BrokerKill(Action):
    name = "broker_kill"

    def __init__(self, target: int | str = "any"):
        self.target = target

    def resolve(self, ctx, rng):
        t = self.target
        if isinstance(t, int):
            b = t
        elif t == "any":
            alive = ctx.cluster.alive_brokers()
            if len(alive) <= ctx.min_alive:
                return {"broker": None, "skipped": "min_alive"}
            b = rng.choice(sorted(alive))
        elif t == "controller":
            b = ctx.cluster.controller_id
        elif t.startswith("coordinator:"):
            b = ctx.cluster.coordinator_for(t.split(":", 1)[1])
        elif t.startswith("leader:"):
            _, topic, part = t.split(":")
            b = ctx.cluster.partition(topic, int(part)).leader
        else:
            raise ValueError(f"broker_kill target {t!r}")
        if b in ctx.killed:
            return {"broker": None, "skipped": "already_down"}
        return {"broker": b}

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        info = ctx.cluster.kill_broker(b)
        ctx.killed.append(b)
        resolved["migrated"] = len(info["migrated"])


class _BrokerRestart(Action):
    name = "broker_restart"

    def __init__(self, target: int | str = "killed"):
        self.target = target

    def resolve(self, ctx, rng):
        if isinstance(self.target, int):
            return {"broker": self.target}
        # "killed": revive in kill order (FIFO) — the rolling-restart
        # shape; a restart with nothing down is a recorded no-op
        if not ctx.killed:
            return {"broker": None, "skipped": "none_down"}
        return {"broker": ctx.killed[0]}

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        ctx.cluster.restart_broker(b)
        if b in ctx.killed:
            ctx.killed.remove(b)


class _ProcKill9(_BrokerKill):
    """SIGKILL the broker's OS process (out-of-process tier:
    ``ClusterHandle.kill9`` really ``kill -9``s the relay; in-process
    ``MockCluster.kill9`` applies the same controller reaction).
    Target grammar and min_alive quorum floor are _BrokerKill's."""

    name = "proc_kill9"

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        info = ctx.cluster.kill9(b)
        ctx.killed.append(b)
        if isinstance(info, dict):
            resolved["migrated"] = len(info.get("migrated") or [])


class _ProcRestart(_BrokerRestart):
    """Respawn a killed broker process (same public port, fresh pid);
    in-process this is ``restart_broker``. Distinct timeline name so
    storms read honestly in either tier."""

    name = "proc_restart"


class _ProcPause(Action):
    """SIGSTOP the broker's process — the GC-pause/VM-freeze brownout:
    connects still succeed (kernel backlog) but nothing is served, so
    clients walk the request-timeout path instead of connect-refused.
    Resolution mirrors broker_kill's target grammar; a broker already
    paused or down is skipped, and ``min_alive`` counts only brokers
    that are both alive AND unpaused (a fully-frozen cluster would
    stall the storm clock itself)."""

    name = "proc_pause"

    def __init__(self, target: int | str = "any"):
        self.target = target

    def resolve(self, ctx, rng):
        t = self.target
        responsive = [b for b in ctx.cluster.alive_brokers()
                      if b not in ctx.paused]
        if isinstance(t, int):
            b = t
        elif t == "any":
            if len(responsive) <= ctx.min_alive:
                return {"broker": None, "skipped": "min_alive"}
            b = rng.choice(sorted(responsive))
        elif t == "controller":
            b = ctx.cluster.controller_id
        elif t.startswith("coordinator:"):
            b = ctx.cluster.coordinator_for(t.split(":", 1)[1])
        elif t.startswith("leader:"):
            _, topic, part = t.split(":")
            b = ctx.cluster.partition(topic, int(part)).leader
        else:
            raise ValueError(f"proc_pause target {t!r}")
        if b in ctx.paused or b in ctx.killed:
            return {"broker": None, "skipped": "unavailable"}
        return {"broker": b}

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        ctx.cluster.pause_broker(b)
        ctx.paused.append(b)


class _ProcCont(Action):
    """SIGCONT — thaw a paused broker process. ``"paused"`` resumes in
    pause order (FIFO, the brownout-ends shape)."""

    name = "proc_cont"

    def __init__(self, target: int | str = "paused"):
        self.target = target

    def resolve(self, ctx, rng):
        if isinstance(self.target, int):
            return {"broker": self.target}
        if not ctx.paused:
            return {"broker": None, "skipped": "none_paused"}
        return {"broker": ctx.paused[0]}

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        ctx.cluster.resume_broker(b)
        if b in ctx.paused:
            ctx.paused.remove(b)


def _resolve_broker(ctx, rng, target, *, busy=(), floor: bool = True):
    """Shared target grammar for the environment-fault verbs (the
    _BrokerKill grammar): int passthrough, ``"any"`` rng-drawn from
    the responsive pool, ``"controller"``, ``"coordinator:<key>"``,
    ``"leader:t:p"``.  The responsive pool excludes every degraded
    broker — paused, in an EIO window, browned, plus the verb's own
    ``busy`` list — and ``floor`` applies the min_alive quorum rule
    to "any": the pool AFTER this fault must stay above it (two
    different env faults may not jointly freeze the quorum)."""
    degraded = (set(ctx.paused) | set(ctx.eio) | set(ctx.browned)
                | set(busy))
    responsive = [b for b in ctx.cluster.alive_brokers()
                  if b not in degraded]
    if isinstance(target, int):
        return {"broker": target}
    if target == "any":
        if floor and len(responsive) <= ctx.min_alive:
            return {"broker": None, "skipped": "min_alive"}
        if not responsive:
            return {"broker": None, "skipped": "none_responsive"}
        return {"broker": rng.choice(sorted(responsive))}
    if target == "controller":
        return {"broker": ctx.cluster.controller_id}
    if target.startswith("coordinator:"):
        return {"broker":
                ctx.cluster.coordinator_for(target.split(":", 1)[1])}
    if target.startswith("leader:"):
        _, topic, part = target.split(":")
        return {"broker": ctx.cluster.partition(topic, int(part)).leader}
    raise ValueError(f"env verb target {target!r}")


class _EnvEio(Action):
    """Disk-full/EIO window on the storage plane: Produce on the
    target broker returns KAFKA_STORAGE_ERROR (retriable — exactly a
    real broker's failed-log-dir reaction) until env_eio_clear or
    heal().  An EIO'd broker cannot accept writes, so it counts
    against the quorum floor like a paused one."""

    name = "env_eio"

    def __init__(self, target: int | str = "any"):
        self.target = target

    def resolve(self, ctx, rng):
        r = _resolve_broker(ctx, rng, self.target, busy=ctx.eio)
        b = r.get("broker")
        if b is not None and (b in ctx.eio or b in ctx.killed):
            return {"broker": None, "skipped": "unavailable"}
        return r

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        ctx.cluster.set_storage_error(b, True)
        ctx.eio.append(b)


class _EnvEioClear(Action):
    """Heal an EIO window; ``"eio"`` heals in fault order (FIFO)."""

    name = "env_eio_clear"

    def __init__(self, target: int | str = "eio"):
        self.target = target

    def resolve(self, ctx, rng):
        if isinstance(self.target, int):
            return {"broker": self.target}
        if not ctx.eio:
            return {"broker": None, "skipped": "none_eio"}
        return {"broker": ctx.eio[0]}

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        ctx.cluster.set_storage_error(b, False)
        if b in ctx.eio:
            ctx.eio.remove(b)


class _EnvSkew(Action):
    """Clock-skew fault: the target broker's wall clock reads
    ``skew_ms`` off true.  No quorum impact (a skewed broker still
    serves); heal() restores every clock."""

    name = "env_skew"

    def __init__(self, skew_ms: float, target: int | str = "any"):
        self.skew_ms = skew_ms
        self.target = target

    def resolve(self, ctx, rng):
        r = _resolve_broker(ctx, rng, self.target,
                            busy=[b for b, _s in ctx.skewed], floor=False)
        if r.get("broker") is not None:
            r["skew_ms"] = self.skew_ms
        return r

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        ctx.cluster.set_clock_skew(b, self.skew_ms)
        ctx.skewed.append((b, self.skew_ms))


class _EnvRlimit(Action):
    """Memory pressure: soft RLIMIT_AS on the target broker's relay
    OS process (out-of-process tier only — the in-process mock has no
    per-broker process, so applying there records a schedule error).
    ``nbytes=0`` would be a heal; heal() restores infinity."""

    name = "env_rlimit"

    def __init__(self, nbytes: int, target: int | str = "any"):
        self.nbytes = nbytes
        self.target = target

    def resolve(self, ctx, rng):
        r = _resolve_broker(ctx, rng, self.target, busy=ctx.rlimited,
                            floor=False)
        if r.get("broker") is not None:
            r["rlim_bytes"] = self.nbytes
        return r

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        ctx.cluster.set_rlimit(b, self.nbytes)
        ctx.rlimited.append(b)


class _EnvBrownout(Action):
    """Asymmetric-partition brownout: one-direction drop/latency on
    the target broker's relay (ClusterHandle.brownout — the
    out-of-process sockem rx_drop/tx_drop analog).  A browned broker
    may be unable to serve (full one-direction drop), so it counts
    against the quorum floor."""

    name = "env_brownout"

    def __init__(self, target: int | str = "any", *,
                 rx_drop: bool = False, tx_drop: bool = False,
                 rx_delay_ms: float = 0.0, tx_delay_ms: float = 0.0):
        self.target = target
        self.knobs = {"rx_drop": rx_drop, "tx_drop": tx_drop,
                      "rx_delay_ms": rx_delay_ms,
                      "tx_delay_ms": tx_delay_ms}

    def resolve(self, ctx, rng):
        r = _resolve_broker(ctx, rng, self.target, busy=ctx.browned)
        b = r.get("broker")
        if b is not None and (b in ctx.browned or b in ctx.killed):
            return {"broker": None, "skipped": "unavailable"}
        if b is not None:
            r.update(self.knobs)
        return r

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        ctx.cluster.brownout(b, **self.knobs)
        ctx.browned.append(b)


class _EnvBrownoutClear(Action):
    """End a brownout; ``"browned"`` clears in fault order (FIFO)."""

    name = "env_brownout_clear"

    def __init__(self, target: int | str = "browned"):
        self.target = target

    def resolve(self, ctx, rng):
        if isinstance(self.target, int):
            return {"broker": self.target}
        if not ctx.browned:
            return {"broker": None, "skipped": "none_browned"}
        return {"broker": ctx.browned[0]}

    def apply(self, ctx, resolved):
        b = resolved.get("broker")
        if b is None:
            return
        ctx.cluster.clear_brownout(b)
        if b in ctx.browned:
            ctx.browned.remove(b)


class _LeaderMigrate(Action):
    name = "leader_migrate"

    def __init__(self, topic: str, partition: int | str = "any",
                 to: int | str = "any_other"):
        self.topic = topic
        self.partition = partition
        self.to = to

    def resolve(self, ctx, rng):
        parts = ctx.cluster.topics[self.topic]
        pnum = (self.partition if isinstance(self.partition, int)
                else rng.choice(range(len(parts))))
        cur = parts[pnum].leader
        if isinstance(self.to, int):
            to = self.to
        else:
            cands = sorted(b for b in ctx.cluster.alive_brokers()
                           if b != cur)
            if not cands:
                return {"partition": pnum, "to": None,
                        "skipped": "no_candidate"}
            to = rng.choice(cands)
        return {"topic": self.topic, "partition": pnum,
                "from": cur, "to": to}

    def apply(self, ctx, resolved):
        if resolved.get("to") is None:
            return
        ctx.cluster.set_partition_leader(
            resolved["topic"], resolved["partition"], resolved["to"])


class _Net(Action):
    """Live sockem re-shaping: any subset of delay/jitter/rate/
    max_write/rx_drop/tx_drop (None = leave unchanged)."""

    name = "net"

    def __init__(self, **knobs):
        self.knobs = knobs

    def resolve(self, ctx, rng):
        return dict(self.knobs)

    def apply(self, ctx, resolved):
        if ctx.sockem is None:
            raise RuntimeError("net() step requires a Sockem in the "
                               "ChaosScheduler (sockem=...)")
        ctx.sockem.set(**resolved)


class _ConnKill(Action):
    name = "conn_kill"

    def __init__(self, count: Optional[int] = None):
        self.count = count

    def resolve(self, ctx, rng):
        return {"count": self.count}

    def apply(self, ctx, resolved):
        if ctx.sockem is None:
            raise RuntimeError("conn_kill() step requires a Sockem in "
                               "the ChaosScheduler (sockem=...)")
        resolved["killed"] = ctx.sockem.kill(self.count)


class _Call(Action):
    """Escape hatch: run an arbitrary callable(ctx) — scenario-local
    faults (e.g. pushing a scripted error stack) without a new verb."""

    name = "call"

    def __init__(self, fn, label: str = ""):
        self.fn = fn
        self.label = label or getattr(fn, "__name__", "fn")

    def resolve(self, ctx, rng):
        return {"label": self.label}

    def apply(self, ctx, resolved):
        self.fn(ctx)


# DSL constructors (the schedule is data; these just read better than
# class names at call sites)
def broker_kill(target: int | str = "any") -> Action:
    return _BrokerKill(target)


def broker_restart(target: int | str = "killed") -> Action:
    return _BrokerRestart(target)


def proc_kill9(target: int | str = "any") -> Action:
    return _ProcKill9(target)


def proc_restart(target: int | str = "killed") -> Action:
    return _ProcRestart(target)


def proc_pause(target: int | str = "any") -> Action:
    return _ProcPause(target)


def proc_cont(target: int | str = "paused") -> Action:
    return _ProcCont(target)


def env_eio(target: int | str = "any") -> Action:
    return _EnvEio(target)


def env_eio_clear(target: int | str = "eio") -> Action:
    return _EnvEioClear(target)


def env_skew(skew_ms: float, target: int | str = "any") -> Action:
    return _EnvSkew(skew_ms, target)


def env_rlimit(nbytes: int, target: int | str = "any") -> Action:
    return _EnvRlimit(nbytes, target)


def env_brownout(target: int | str = "any", **knobs) -> Action:
    return _EnvBrownout(target, **knobs)


def env_brownout_clear(target: int | str = "browned") -> Action:
    return _EnvBrownoutClear(target)


def leader_migrate(topic: str, partition: int | str = "any",
                   to: int | str = "any_other") -> Action:
    return _LeaderMigrate(topic, partition, to)


def net(**knobs) -> Action:
    return _Net(**knobs)


def conn_kill(count: Optional[int] = None) -> Action:
    return _ConnKill(count)


def call(fn, label: str = "") -> Action:
    return _Call(fn, label)


# ------------------------------------------------------------ schedule --
@dataclass
class Step:
    t: float
    action: Action
    idx: int = 0


class Schedule:
    """An ordered fault script. ``at`` is chainable; ``every`` expands
    to repeated steps at build time so the executed step list — and
    therefore rng consumption order — is fixed before the storm."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.steps: list[Step] = []

    def at(self, t: float, action: Action) -> "Schedule":
        self.steps.append(Step(t=float(t), action=action,
                               idx=len(self.steps)))
        return self

    def every(self, start: float, interval: float, count: int,
              make_action) -> "Schedule":
        """``make_action``: an Action (reused) or a zero-arg factory
        (fresh Action per repeat)."""
        for i in range(count):
            a = make_action() if callable(make_action) \
                and not isinstance(make_action, Action) else make_action
            self.at(start + i * interval, a)
        return self

    def sorted_steps(self) -> list[Step]:
        return sorted(self.steps, key=lambda s: (s.t, s.idx))

    @property
    def duration(self) -> float:
        return max((s.t for s in self.steps), default=0.0)


# ----------------------------------------------------------- execution --
@dataclass
class ChaosContext:
    cluster: object
    sockem: object = None
    #: broker_kill("any") never drops the alive count below this —
    #: storms that must keep quorum (a 1-broker cluster can't serve)
    min_alive: int = 1
    #: brokers currently down, in kill order (broker_restart FIFO)
    killed: list = field(default_factory=list)
    #: brokers currently SIGSTOPped, in pause order (proc_cont FIFO)
    paused: list = field(default_factory=list)
    #: brokers in an EIO/disk-full window (env_eio_clear FIFO)
    eio: list = field(default_factory=list)
    #: (broker, skew_ms) clock-skew faults in effect
    skewed: list = field(default_factory=list)
    #: brokers whose relay carries a lowered RLIMIT_AS
    rlimited: list = field(default_factory=list)
    #: brokers under an asymmetric brownout (env_brownout_clear FIFO)
    browned: list = field(default_factory=list)


class ChaosScheduler:  # lint: ok shared-state
    """Executes a Schedule on its own thread ("chaos-sched-*": the
    conftest leak fixture fails any test that leaves one alive).

    shared-state pragma: the timeline and ctx books are written only
    by the scheduler thread; storms read them after join()/stop() (a
    happens-before edge), and heal() runs post-join on the storm
    thread.

    ``timeline`` records every step as it fires:
    ``{"idx", "t", "action", "resolved", "wall", "error"}`` — ``idx``/
    ``t``/``action``/``resolved`` are the deterministic replay key,
    ``wall`` is the observed offset (diagnostics only)."""

    _seq = 0

    def __init__(self, cluster, sockem=None, *, min_alive: int = 1,
                 name: Optional[str] = None):
        self.ctx = ChaosContext(cluster=cluster, sockem=sockem,
                                min_alive=min_alive)
        ChaosScheduler._seq += 1
        self.name = name or f"chaos-sched-{ChaosScheduler._seq}"
        self.timeline: list[dict] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- run --------------------------------------------------------------
    def start(self, schedule: Schedule) -> "ChaosScheduler":
        assert self._thread is None, "scheduler already started"
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, args=(schedule,), name=self.name, daemon=True)
        self._thread.start()
        return self

    def run(self, schedule: Schedule) -> list[dict]:
        """Synchronous execution (no thread) — used by the replay
        determinism tests and anywhere the caller owns the clock."""
        self._execute(schedule, wait=False)
        return self.timeline

    def _run(self, schedule: Schedule) -> None:
        self._execute(schedule, wait=True)

    def _execute(self, schedule: Schedule, wait: bool) -> None:
        rng = random.Random(schedule.seed)
        t0 = time.monotonic()
        for step in schedule.sorted_steps():
            if wait:
                delay = t0 + step.t - time.monotonic()
                if delay > 0 and self._stop.wait(delay):
                    break
            if self._stop.is_set():
                break
            entry = {"idx": step.idx, "t": step.t,
                     "action": step.action.name,
                     "wall": round(time.monotonic() - t0, 4),
                     # absolute monotonic stamp: recovery-latency
                     # metrics correlate kills with the oracle's ack
                     # timestamps (excluded from the replay key)
                     "mono": time.monotonic()}
            try:
                resolved = step.action.resolve(self.ctx, rng)
                entry["resolved"] = resolved
                step.action.apply(self.ctx, resolved)
                if _metrics.enabled:
                    _metrics.counter("chaos.faults_fired").inc()
            except Exception as e:          # record, don't kill the storm
                entry["error"] = repr(e)
            self.timeline.append(entry)

    # -- lifecycle --------------------------------------------------------
    def join(self, timeout: float = 30.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
            assert not self._thread.is_alive(), \
                f"chaos scheduler {self.name} did not finish"
            self._thread = None

    def stop(self) -> None:
        """Abort remaining steps and join (idempotent)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def heal(self) -> None:
        """Restore a healthy cluster after the storm: thaw every
        paused broker, restart every broker the schedule left down,
        clear sockem shaping, and lift every environment fault (EIO
        windows, clock skew, rlimits, brownouts) — the drain phase
        must measure delivery, not leftover faults."""
        for b in list(self.ctx.paused):
            self.ctx.cluster.resume_broker(b)
            self.ctx.paused.remove(b)
        for b in list(self.ctx.killed):
            self.ctx.cluster.restart_broker(b)
            self.ctx.killed.remove(b)
        for b in list(self.ctx.eio):
            self.ctx.cluster.set_storage_error(b, False)
            self.ctx.eio.remove(b)
        for b, _skew in list(self.ctx.skewed):
            self.ctx.cluster.set_clock_skew(b, 0.0)
            self.ctx.skewed.remove((b, _skew))
        for b in list(self.ctx.rlimited):
            self.ctx.cluster.set_rlimit(b, 0)
            self.ctx.rlimited.remove(b)
        for b in list(self.ctx.browned):
            self.ctx.cluster.clear_brownout(b)
            self.ctx.browned.remove(b)
        if self.ctx.sockem is not None:
            self.ctx.sockem.set(delay_ms=0, jitter_ms=0, rate_bps=0,
                                max_write=0, rx_drop=False, tx_drop=False)

    # -- replay -----------------------------------------------------------
    def replay_key(self) -> list[tuple]:
        """The deterministic projection of the timeline: equal across
        runs with the same schedule + seed (the CHAOS.md replay
        contract); wall-clock offsets and counters are excluded."""
        out = []
        for e in self.timeline:
            res = e.get("resolved") or {}
            stable = tuple(sorted(
                (k, v) for k, v in res.items()
                if k in ("broker", "topic", "partition", "from", "to",
                         "skipped", "count", "label")
                or k in ("delay_ms", "jitter_ms", "rate_bps", "max_write",
                         "rx_drop", "tx_drop")
                # environment fault library (ISSUE 11)
                or k in ("skew_ms", "rlim_bytes", "rx_delay_ms",
                         "tx_delay_ms")))
            out.append((e["idx"], e["t"], e["action"], stable))
        return out

    @property
    def errors(self) -> list[dict]:
        return [e for e in self.timeline if "error" in e]
