"""Delivery-invariant oracle: the ledger that decides whether a chaos
storm actually broke anything.

Records every **acked** produce (topic, partition, offset, key, value,
txn id — fed by the delivery-report callback, so only what the broker
confirmed counts) and every **consumed** message, then asserts the
delivery contract after the storm:

  * **zero acked loss** — every committed ack is consumed;
  * **zero duplication** — under EOS ``read_committed`` no record is
    delivered twice;
  * **per-partition order** — records of one partition arrive in
    offset order, the order they were acked in;
  * **txn atomicity** — a transaction's records land all-or-nothing:
    committed txns fully visible, aborted txns fully invisible.

On any violation the oracle dumps the PR-5 flight recorder (the trace
that *explains* the failure) plus its own diff as JSON, then raises
``OracleViolation`` carrying the structured report — the chaos analog
of the fetch path's CRC-mismatch flight trigger.

Identity: message **values must be unique per oracle** (scenario
producers stamp a monotonically increasing sequence into each value);
loss/dup/order are judged on ``(topic, partition, value)``.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Optional

from ..obs import trace
from ..analysis.locks import new_lock


class OracleViolation(AssertionError):
    """Delivery contract broken; ``.report`` holds the full verdict
    (violations, flight-recorder path, oracle-diff path)."""

    def __init__(self, report: dict):
        self.report = report
        v = report["violations"]
        summary = ", ".join(f"{k}={len(rows)}" for k, rows in v.items()
                            if rows)
        super().__init__(
            f"delivery invariants violated ({summary}); "
            f"oracle diff: {report.get('diff_path')}, "
            f"flight dump: {report.get('flight_path')}")


#: cap per-violation rows carried in the in-memory report / exception;
#: the JSON diff on disk always holds everything
REPORT_ROW_CAP = 50


class DeliveryOracle:
    """Thread-safe ledger (DR callbacks fire on client poll threads,
    consumers record from their own loops)."""

    def __init__(self, *, dump_dir: Optional[str] = None):
        self._lock = new_lock("chaos.oracle")
        self.dump_dir = dump_dir
        # acked produces: (topic, partition, offset, key, value, txn)
        self.acked: list[tuple] = []
        # produce failures: (topic, partition, value, txn, err_str) —
        # not required to be delivered, kept for the report
        self.failed: list[tuple] = []
        # consumed: (topic, partition, offset, value) in arrival order
        self.consumed: list[tuple] = []
        # txn id -> "open" | "committed" | "aborted" | "unknown"
        self.txns: dict[str, str] = {}

    # ---------------------------------------------------- producer side --
    def dr(self, txn: Optional[str] = None):
        """A delivery-report callback bound to ``txn`` (None = plain
        produce): ``produce(..., on_delivery=oracle.dr(tid))``."""
        def _cb(err, msg):
            if err is None:
                self.record_ack(msg.topic, msg.partition, msg.offset,
                                msg.key, msg.value, txn)
            else:
                with self._lock:
                    self.failed.append((msg.topic, msg.partition,
                                        msg.value, txn, str(err)))
        return _cb

    def record_ack(self, topic: str, partition: int, offset: int,
                   key: Optional[bytes], value: Optional[bytes],
                   txn: Optional[str] = None) -> None:
        with self._lock:
            self.acked.append((topic, partition, offset, key, value, txn))

    def begin_txn(self, txn: str) -> None:
        with self._lock:
            self.txns[txn] = "open"

    def commit_txn(self, txn: str) -> None:
        with self._lock:
            self.txns[txn] = "committed"

    def abort_txn(self, txn: str) -> None:
        with self._lock:
            self.txns[txn] = "aborted"

    def unknown_txn(self, txn: str) -> None:
        """Outcome undeterminable client-side (commit AND abort both
        errored mid-storm): its records are exempt from loss/dup checks
        but still must land atomically; storms assert this stays 0."""
        with self._lock:
            self.txns[txn] = "unknown"

    # ---------------------------------------------------- consumer side --
    def record_consumed(self, msg) -> None:
        """Feed one consumed Message (or anything with topic/partition/
        offset/value attributes)."""
        with self._lock:
            self.consumed.append((msg.topic, msg.partition, msg.offset,
                                  msg.value))

    # ---------------------------------------------------------- verdict --
    def stats(self) -> dict:
        with self._lock:
            committed = sum(1 for *_x, txn in self.acked
                            if txn is None
                            or self.txns.get(txn) == "committed")
            return {"acked": len(self.acked),
                    "acked_committed": committed,
                    "consumed": len(self.consumed),
                    "failed": len(self.failed),
                    "txns": dict(self.txns)}

    def _committed(self, txn: Optional[str]) -> bool:
        return txn is None or self.txns.get(txn) == "committed"

    def missing_count(self) -> int:
        """Committed acks not yet consumed — the drain phase polls
        until this reaches 0 (or its deadline: that's a loss)."""
        with self._lock:
            have = {(t, p, v) for t, p, _o, v in self.consumed}
            return sum(1 for t, p, _o, _k, v, txn in self.acked
                       if self._committed(txn) and (t, p, v) not in have)

    def verify(self, *, check_duplicates: bool = True,
               check_order: bool = True,
               raise_on_violation: bool = True) -> dict:
        """Judge the ledger. Scenarios without exactly-once semantics
        (plain consumer-group rebalances are at-least-once) relax
        ``check_duplicates``/``check_order``; loss and txn atomicity
        are always enforced."""
        with self._lock:
            acked = list(self.acked)
            consumed = list(self.consumed)
            txns = dict(self.txns)
            failed = list(self.failed)

        lost, duplicated, reordered = [], [], []
        aborted_seen, torn = [], []

        consumed_count: dict[tuple, int] = {}
        for topic, part, off, value in consumed:
            consumed_count[(topic, part, value)] = \
                consumed_count.get((topic, part, value), 0) + 1

        # -- zero acked-message loss (committed/plain acks only) ----------
        for topic, part, off, key, value, txn in acked:
            st = txns.get(txn) if txn is not None else None
            if txn is not None and st != "committed":
                continue
            if (topic, part, value) not in consumed_count:
                lost.append({"topic": topic, "partition": part,
                             "offset": off, "txn": txn,
                             "value": _short(value)})

        # -- zero duplication (EOS read_committed) ------------------------
        if check_duplicates:
            for (topic, part, value), n in consumed_count.items():
                if n > 1:
                    duplicated.append({"topic": topic, "partition": part,
                                       "count": n, "value": _short(value)})

        # -- per-partition ordering ---------------------------------------
        if check_order:
            last: dict[tuple, tuple] = {}
            for topic, part, off, value in consumed:
                prev = last.get((topic, part))
                if prev is not None and off <= prev[0]:
                    reordered.append(
                        {"topic": topic, "partition": part,
                         "offset": off, "after_offset": prev[0],
                         "value": _short(value)})
                last[(topic, part)] = (off, value)

        # -- txn visibility + atomicity -----------------------------------
        by_txn: dict[str, list] = {}
        for topic, part, off, key, value, txn in acked:
            if txn is not None:
                by_txn.setdefault(txn, []).append((topic, part, value))
        for txn, msgs in by_txn.items():
            st = txns.get(txn, "open")
            seen = sum(1 for m in msgs if m in consumed_count)
            if st == "aborted" and seen:
                for topic, part, value in msgs:
                    if (topic, part, value) in consumed_count:
                        aborted_seen.append(
                            {"txn": txn, "topic": topic, "partition": part,
                             "value": _short(value)})
            # all-or-nothing regardless of which outcome won
            if 0 < seen < len(msgs):
                torn.append({"txn": txn, "state": st,
                             "acked": len(msgs), "consumed": seen})

        violations = {"lost": lost, "duplicated": duplicated,
                      "reordered": reordered,
                      "aborted_seen": aborted_seen, "torn_txns": torn}
        ok = not any(violations.values())
        report = {
            "ok": ok,
            "acked": len(acked), "consumed": len(consumed),
            "failed_produces": len(failed),
            "txns": {"committed":
                     sum(1 for s in txns.values() if s == "committed"),
                     "aborted":
                     sum(1 for s in txns.values() if s == "aborted"),
                     "unknown":
                     sum(1 for s in txns.values() if s == "unknown"),
                     "open":
                     sum(1 for s in txns.values() if s == "open")},
            "violations": {k: v[:REPORT_ROW_CAP]
                           for k, v in violations.items()},
        }
        if not ok:
            report["diff_path"] = self._dump_diff(violations, report)
            # the trace that explains the storm must survive it: stamp
            # the verdict into the rings, then flight-dump them
            # (flight_record self-checks and returns None when tracing
            # is off, so the key is present either way)
            if trace.enabled:
                trace.instant("chaos", "oracle_violation",
                              {k: len(v) for k, v in violations.items()})
            report["flight_path"] = trace.flight_record("oracle_violation")
            if raise_on_violation:
                raise OracleViolation(report)
        return report

    def _dump_diff(self, violations: dict, report: dict) -> Optional[str]:
        d = self.dump_dir or tempfile.gettempdir()
        path = os.path.join(
            d, f"tk_oracle_{os.getpid()}_{id(self) & 0xFFFF:x}.json")
        try:
            with open(path, "w") as f:
                json.dump({"summary": {k: len(v)
                                       for k, v in violations.items()},
                           "stats": {"acked": report["acked"],
                                     "consumed": report["consumed"],
                                     "txns": report["txns"]},
                           "violations": violations}, f, indent=1,
                          default=_short)
        except OSError:
            return None
        return path


def _short(v) -> str:
    if isinstance(v, (bytes, bytearray)):
        return v[:48].decode("latin1")
    return str(v)[:64]
