"""Delivery-invariant oracle: the ledger that decides whether a chaos
storm actually broke anything.

Records every **acked** produce (topic, partition, offset, key, value,
txn id — fed by the delivery-report callback, so only what the broker
confirmed counts) and every **consumed** message, then asserts the
delivery contract after the storm:

  * **zero acked loss** — every committed ack is consumed;
  * **zero duplication** — under EOS ``read_committed`` no record is
    delivered twice;
  * **per-partition order** — records of one partition arrive in
    offset order, the order they were acked in;
  * **txn atomicity** — a transaction's records land all-or-nothing:
    committed txns fully visible, aborted txns fully invisible.

On any violation the oracle dumps the PR-5 flight recorder (the trace
that *explains* the failure) plus its own diff as JSON, then raises
``OracleViolation`` carrying the structured report — the chaos analog
of the fetch path's CRC-mismatch flight trigger.

Identity: message **values must be unique per oracle** (scenario
producers stamp a monotonically increasing sequence into each value);
loss/dup/order are judged on ``(topic, partition, value)``.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Optional

from ..obs import trace
from ..analysis.locks import new_lock
from ..analysis.races import shared_dict, shared_list


class OracleViolation(AssertionError):
    """Delivery contract broken; ``.report`` holds the full verdict
    (violations, flight-recorder path, oracle-diff path)."""

    def __init__(self, report: dict):
        self.report = report
        v = report["violations"]
        summary = ", ".join(f"{k}={len(rows)}" for k, rows in v.items()
                            if rows)
        super().__init__(
            f"delivery invariants violated ({summary}); "
            f"oracle diff: {report.get('diff_path')}, "
            f"flight dump: {report.get('flight_path')}")


#: cap per-violation rows carried in the in-memory report / exception;
#: the JSON diff on disk always holds everything
REPORT_ROW_CAP = 50


class DeliveryOracle:
    """Thread-safe ledger (DR callbacks fire on client poll threads,
    consumers record from their own loops)."""

    def __init__(self, *, dump_dir: Optional[str] = None,
                 track_flow: bool = False):
        self._lock = new_lock("chaos.oracle")
        self.dump_dir = dump_dir
        #: continuity tracking (ISSUE 12): per-partition consumption
        #: stamps + per-member rebalance windows feed the flow-gap
        #: detector (``verify(check_continuity=True)``) — opt-in, the
        #: stamps are per-message state other storms don't need
        self.track_flow = track_flow
        # every ledger is declared shared (analysis/races.py): DR
        # callbacks append from broker/poll threads, consumers from
        # their own loops, the verdict snapshots from the storm thread
        # — all under chaos.oracle, and the lockset sweep keeps the
        # discipline honest (an unlocked append from a new callback
        # path is an empty-lockset write)
        # acked produces: (topic, partition, offset, key, value, txn)
        self.acked: list[tuple] = shared_list("oracle.acked")
        # produce failures: (topic, partition, value, txn, err_str) —
        # not required to be delivered, kept for the report
        self.failed: list[tuple] = shared_list("oracle.failed")
        # consumed: (topic, partition, offset, value) in arrival order
        self.consumed: list[tuple] = shared_list("oracle.consumed")
        # txn id -> "open" | "committed" | "aborted" | "unknown"
        self.txns: dict[str, str] = shared_dict("oracle.txns")
        # monotonic stamp per acked row (parallel to ``acked``): feeds
        # the storm-metrics recovery clock (time-to-first-ack after a
        # process kill), never the delivery verdict
        self.acked_ts: list[float] = shared_list("oracle.acked_ts")
        # ---- consumer-group ledger (ISSUE 9 group invariants) ----
        # member -> {"assigns": n, "current": set[(t,p)] | None,
        #            "last_poll": ts, "last_assign": ts, "closed": bool}
        self.members: dict[str, dict] = shared_dict("oracle.members")
        # (ts, member, kind, parts|None) for every membership/
        # assignment change — convergence is judged relative to the
        # LAST of these; incremental revokes carry their partition set
        self.group_events: list[tuple] = shared_list("oracle.group_events")
        # ---- continuity ledger (ISSUE 12 flow-gap detector) ----
        # (topic, partition) -> [consume monotonic stamps]
        self.flow: dict[tuple, list] = shared_dict("oracle.flow")
        # closed rebalance windows: (member, start, end, kept frozenset)
        # — ``kept`` is the UNREVOKED ownership the member carried
        # through the window; each kept partition must keep flowing
        self.windows: list[tuple] = shared_list("oracle.windows")
        # member -> (start_ts, kept set) while a revoke awaits the
        # member's next assignment
        self._open_windows: dict[str, tuple] = shared_dict(
            "oracle.open_windows")

    # ---------------------------------------------------- producer side --
    def dr(self, txn: Optional[str] = None):
        """A delivery-report callback bound to ``txn`` (None = plain
        produce): ``produce(..., on_delivery=oracle.dr(tid))``."""
        def _cb(err, msg):
            if err is None:
                self.record_ack(msg.topic, msg.partition, msg.offset,
                                msg.key, msg.value, txn)
            else:
                with self._lock:
                    self.failed.append((msg.topic, msg.partition,
                                        msg.value, txn, str(err)))
        return _cb

    def record_ack(self, topic: str, partition: int, offset: int,
                   key: Optional[bytes], value: Optional[bytes],
                   txn: Optional[str] = None,
                   ts: Optional[float] = None) -> None:
        """``ts``: the ack's ``time.monotonic()`` stamp.  In-process
        callers omit it (stamped on arrival); the fleet driver passes
        the WORKER's stamp so recovery envelopes measure the client's
        clock, not the merge pipeline's batching latency."""
        with self._lock:
            self.acked.append((topic, partition, offset, key, value, txn))
            self.acked_ts.append(time.monotonic() if ts is None else ts)

    def record_failed(self, topic: str, partition: int,
                      value, txn: Optional[str], err: str) -> None:
        with self._lock:
            self.failed.append((topic, partition, value, txn, err))

    # ------------------------------------------- fleet ledger merge --
    def record_acks(self, rows) -> None:
        """Bulk merge of a fleet worker's streamed ack ledger: rows of
        ``(topic, partition, offset, key, value, txn, ts)`` land under
        one lock acquisition (hundreds of workers stream batches; a
        per-row lock would make the merge the bottleneck)."""
        with self._lock:
            for topic, partition, offset, key, value, txn, ts in rows:
                self.acked.append((topic, partition, offset, key, value,
                                   txn))
                self.acked_ts.append(ts)

    def record_consumed_rows(self, rows) -> None:
        """Bulk merge of consumed rows ``(topic, partition, offset,
        value[, ts])`` — the consumer-side half of ``record_acks``;
        the optional worker-side stamp feeds the continuity ledger."""
        with self._lock:
            for row in rows:
                topic, partition, offset, value = row[:4]
                self.consumed.append((topic, partition, offset, value))
                if self.track_flow:
                    ts = row[4] if len(row) > 4 else time.monotonic()
                    self.flow.setdefault((topic, partition),
                                         []).append(ts)

    def begin_txn(self, txn: str) -> None:
        with self._lock:
            self.txns[txn] = "open"

    def commit_txn(self, txn: str) -> None:
        with self._lock:
            self.txns[txn] = "committed"

    def abort_txn(self, txn: str) -> None:
        with self._lock:
            self.txns[txn] = "aborted"

    def unknown_txn(self, txn: str) -> None:
        """Outcome undeterminable client-side (commit AND abort both
        errored mid-storm): its records are exempt from loss/dup checks
        but still must land atomically; storms assert this stays 0."""
        with self._lock:
            self.txns[txn] = "unknown"

    # ---------------------------------------------------- consumer side --
    def record_consumed(self, msg) -> None:
        """Feed one consumed Message (or anything with topic/partition/
        offset/value attributes)."""
        with self._lock:
            self.consumed.append((msg.topic, msg.partition, msg.offset,
                                  msg.value))
            if self.track_flow:
                self.flow.setdefault((msg.topic, msg.partition),
                                     []).append(time.monotonic())

    # ------------------------------------------------------ group side --
    def _member(self, member: str) -> dict:
        st = self.members.get(member)
        if st is None:
            st = self.members[member] = {
                "assigns": 0, "current": None, "last_poll": 0.0,
                "last_assign": 0.0, "closed": False}
        return st

    def record_assign(self, member: str, partitions,
                      incremental: bool = False) -> None:
        """on_assign callback: ``partitions`` is the member's NEW
        ownership set as (topic, partition) pairs (empty is a real
        assignment — a large group legally leaves members idle).
        ``incremental=True`` (KIP-429 cooperative) ADDS to the current
        set instead of replacing it."""
        now = time.monotonic()
        parts = set(partitions)
        with self._lock:
            st = self._member(member)
            st["assigns"] += 1
            if incremental:
                st["current"] = (st["current"] or set()) | parts
            else:
                st["current"] = parts
            st["last_assign"] = now
            self.group_events.append((now, member, "assign",
                                      tuple(sorted(parts))))
            # an assign closes the member's open rebalance window: the
            # kept partitions were required to flow from the revoke
            # delivery until right now
            open_w = self._open_windows.pop(member, None)
            if open_w is not None and self.track_flow:
                start, kept = open_w
                self.windows.append((member, start, now,
                                     frozenset(kept)))

    def record_rebalance_begin(self, member: str) -> None:
        """The member started rebalancing (left steady state / rejoin
        triggered) while still OWNING its current set: opens the
        continuity window — every partition it keeps through the
        rebalance must flow until the next assignment closes the
        window.  Mid-window incremental revokes narrow the kept set
        (``record_revoke``); eager full revokes discard the window
        (an eager member legally stops the world)."""
        now = time.monotonic()
        with self._lock:
            st = self._member(member)
            kept = set(st["current"] or ())
            self.group_events.append((now, member, "rebalance", None))
            if self.track_flow and kept \
                    and member not in self._open_windows:
                self._open_windows[member] = (now, kept)

    def record_revoke(self, member: str, partitions=None) -> None:
        """``partitions=None`` is the eager full revoke (between
        generations the member owns nothing).  A (topic, partition)
        list is a KIP-429 INCREMENTAL revoke: only those leave the
        member's ownership — everything kept is REQUIRED to keep
        flowing until the member's next assignment (the continuity
        invariant's rebalance window)."""
        now = time.monotonic()
        with self._lock:
            st = self._member(member)
            if partitions is None:
                st["current"] = None    # between generations: owns nothing
                self.group_events.append((now, member, "revoke", None))
                # eager stop-the-world: nothing is kept, no continuity
                # obligation survives
                self._open_windows.pop(member, None)
                return
            revoked = set(partitions)
            kept = (st["current"] or set()) - revoked
            st["current"] = kept
            self.group_events.append((now, member, "revoke",
                                      tuple(sorted(revoked))))
            if not self.track_flow:
                return
            prev = self._open_windows.get(member)
            if prev is not None:
                # narrow an open window: revoked partitions owe flow
                # only up to this revoke, the rest to the next assign
                narrowed = prev[1] - revoked
                if narrowed:
                    self._open_windows[member] = (prev[0], narrowed)
                else:
                    self._open_windows.pop(member, None)
            elif kept:
                self._open_windows[member] = (now, set(kept))

    def record_poll(self, member: str) -> None:
        """Liveness heartbeat: the member's consume loop is still
        turning (stamped per loop iteration, stored O(1))."""
        with self._lock:
            self._member(member)["last_poll"] = time.monotonic()

    def record_member_closed(self, member: str) -> None:
        """The member left deliberately (churn departure / shutdown):
        exempt from stuck-consumer and coverage checks — and its open
        rebalance window (if any) is discarded, a departing member
        owes no continuity."""
        now = time.monotonic()
        with self._lock:
            self._member(member)["closed"] = True
            self.group_events.append((now, member, "closed", None))
            self._open_windows.pop(member, None)

    def group_coverage(self, topic: str, n_partitions: int) -> dict:
        """Live snapshot of group assignment state — the convergence
        predicate the storm polls: ``converged`` iff every partition is
        owned by exactly one live, assigned member."""
        with self._lock:
            live = {m: st for m, st in self.members.items()
                    if not st["closed"]}
            owned: dict[tuple, list] = {}
            unassigned = []
            for m, st in live.items():
                if st["current"] is None:
                    unassigned.append(m)
                    continue
                for tp in st["current"]:
                    owned.setdefault(tp, []).append(m)
        expected = {(topic, p) for p in range(n_partitions)}
        missing = sorted(p for (t, p) in expected - set(owned)
                         if t == topic)
        overlaps = {f"{t}:{p}": sorted(ms)
                    for (t, p), ms in owned.items() if len(ms) > 1}
        return {"live_members": len(live), "missing": missing,
                "overlaps": overlaps, "unassigned": sorted(unassigned),
                "converged": (bool(live) and not missing and not overlaps
                              and not unassigned)}

    # ---------------------------------------------------------- verdict --
    def stats(self) -> dict:
        with self._lock:
            committed = sum(1 for *_x, txn in self.acked
                            if txn is None
                            or self.txns.get(txn) == "committed")
            return {"acked": len(self.acked),
                    "acked_committed": committed,
                    "consumed": len(self.consumed),
                    "failed": len(self.failed),
                    "txns": dict(self.txns)}

    def _committed(self, txn: Optional[str]) -> bool:
        return txn is None or self.txns.get(txn) == "committed"

    def missing_count(self) -> int:
        """Committed acks not yet consumed — the drain phase polls
        until this reaches 0 (or its deadline: that's a loss)."""
        with self._lock:
            have = {(t, p, v) for t, p, _o, v in self.consumed}
            return sum(1 for t, p, _o, _k, v, txn in self.acked
                       if self._committed(txn) and (t, p, v) not in have)

    def verify(self, *, check_duplicates: bool = True,
               check_order: bool = True,
               check_group: bool = False,
               group_topic: Optional[str] = None,
               group_partitions: int = 0,
               converged_s: Optional[float] = None,
               converge_bound_s: Optional[float] = None,
               stuck_after_s: float = 8.0,
               check_continuity: bool = False,
               flow_stall_s: float = 2.0,
               coverage: Optional[dict] = None,
               now: Optional[float] = None,
               raise_on_violation: bool = True) -> dict:
        """Judge the ledger. Scenarios without exactly-once semantics
        (plain consumer-group rebalances are at-least-once) relax
        ``check_duplicates``/``check_order``; loss and txn atomicity
        are always enforced.

        ``check_group`` adds the ISSUE-9 consumer-group invariants over
        the assignment ledger: **convergence** (the storm passes its
        measured ``converged_s`` once the group settled, None = never —
        a violation), **coverage** (final live assignments partition
        ``group_topic``'s ``group_partitions`` exactly: nothing
        unowned, nothing double-owned), and **no stuck consumer** (a
        live member must have received at least one assignment and
        polled within ``stuck_after_s`` of the verdict).

        ``coverage``/``now``: the storm freezes its group verdict
        (``group_coverage()`` snapshot + clock) BEFORE shutting its
        consumers down — judging the live recompute instead would see
        the deliberate LeaveGroup cascade of teardown as a coverage
        hole.  When omitted (unit tests), both default to live.

        ``check_continuity`` (ISSUE 12, requires ``track_flow=True``):
        the **zero stop-the-world** invariant — for every rebalance
        window (incremental revoke delivery → the member's next
        assignment), each partition the member KEPT must show
        consumption with no internal gap exceeding ``flow_stall_s``,
        provided traffic (acks) existed in the window.  An unrevoked
        partition that stalls across a rebalance is a ``flow_gap``
        violation.  ``converge_bound_s`` turns a measured-but-slow
        convergence into a violation too."""
        with self._lock:
            acked = list(self.acked)
            acked_ts = list(self.acked_ts)
            consumed = list(self.consumed)
            txns = dict(self.txns)
            failed = list(self.failed)
            members = {m: dict(st) for m, st in self.members.items()}
            windows = list(self.windows)
            flow = {tp: list(ts) for tp, ts in self.flow.items()} \
                if check_continuity else {}

        lost, duplicated, reordered = [], [], []
        aborted_seen, torn = [], []

        consumed_count: dict[tuple, int] = {}
        for topic, part, off, value in consumed:
            consumed_count[(topic, part, value)] = \
                consumed_count.get((topic, part, value), 0) + 1

        # -- zero acked-message loss (committed/plain acks only) ----------
        for topic, part, off, key, value, txn in acked:
            st = txns.get(txn) if txn is not None else None
            if txn is not None and st != "committed":
                continue
            if (topic, part, value) not in consumed_count:
                lost.append({"topic": topic, "partition": part,
                             "offset": off, "txn": txn,
                             "value": _short(value)})

        # -- zero duplication (EOS read_committed) ------------------------
        if check_duplicates:
            for (topic, part, value), n in consumed_count.items():
                if n > 1:
                    duplicated.append({"topic": topic, "partition": part,
                                       "count": n, "value": _short(value)})

        # -- per-partition ordering ---------------------------------------
        if check_order:
            last: dict[tuple, tuple] = {}
            for topic, part, off, value in consumed:
                prev = last.get((topic, part))
                if prev is not None and off <= prev[0]:
                    reordered.append(
                        {"topic": topic, "partition": part,
                         "offset": off, "after_offset": prev[0],
                         "value": _short(value)})
                last[(topic, part)] = (off, value)

        # -- txn visibility + atomicity -----------------------------------
        by_txn: dict[str, list] = {}
        for topic, part, off, key, value, txn in acked:
            if txn is not None:
                by_txn.setdefault(txn, []).append((topic, part, value))
        for txn, msgs in by_txn.items():
            st = txns.get(txn, "open")
            seen = sum(1 for m in msgs if m in consumed_count)
            if st == "aborted" and seen:
                for topic, part, value in msgs:
                    if (topic, part, value) in consumed_count:
                        aborted_seen.append(
                            {"txn": txn, "topic": topic, "partition": part,
                             "value": _short(value)})
            # all-or-nothing regardless of which outcome won
            if 0 < seen < len(msgs):
                torn.append({"txn": txn, "state": st,
                             "acked": len(msgs), "consumed": seen})

        violations = {"lost": lost, "duplicated": duplicated,
                      "reordered": reordered,
                      "aborted_seen": aborted_seen, "torn_txns": torn}

        # -- continuity: zero stop-the-world windows (ISSUE 12) -----------
        if check_continuity:
            ack_stamps: dict[tuple, list] = {}
            for (topic, part, *_rest), ts in zip(acked, acked_ts):
                ack_stamps.setdefault((topic, part), []).append(ts)
            for ts_list in ack_stamps.values():
                ts_list.sort()
            flow_gaps = []
            for member, w0, w1, kept in windows:
                if w1 - w0 <= flow_stall_s:
                    continue        # too short to even hold a gap
                for tp in sorted(kept):
                    stamps = flow.get(tp, ())
                    # traffic gate: the partition must have received
                    # acked produce inside the window, otherwise there
                    # was legitimately nothing to consume
                    if not any(w0 <= t <= w1
                               for t in ack_stamps.get(tp, ())):
                        continue
                    anchors = ([w0]
                               + sorted(t for t in stamps
                                        if w0 <= t <= w1) + [w1])
                    gap = max(b - a for a, b in zip(anchors, anchors[1:]))
                    if gap > flow_stall_s:
                        flow_gaps.append(
                            {"member": member, "topic": tp[0],
                             "partition": tp[1],
                             "gap_s": round(gap, 2),
                             "window_s": round(w1 - w0, 2),
                             "window": [round(w0, 3), round(w1, 3)]})
            violations["flow_gap"] = flow_gaps

        # -- consumer-group invariants (assignment ledger) ----------------
        group_blob = None
        if check_group:
            unconverged, stuck = [], []
            cov = (coverage if coverage is not None else
                   self.group_coverage(group_topic or "",
                                       group_partitions))
            if converged_s is None:
                unconverged.append(
                    {"reason": "no_convergence_within_bound", **{
                        k: cov[k] for k in ("missing", "overlaps",
                                            "unassigned")}})
            elif (converge_bound_s is not None
                    and converged_s > converge_bound_s):
                unconverged.append(
                    {"reason": "convergence_exceeded_bound",
                     "converged_s": converged_s,
                     "bound_s": converge_bound_s})
            else:
                # converged once, but the FINAL state must still hold:
                # a late rebalance may not leave holes or double owners
                if cov["missing"]:
                    unconverged.append({"reason": "uncovered_partitions",
                                        "missing": cov["missing"]})
                if cov["overlaps"]:
                    unconverged.append({"reason": "overlapping_ownership",
                                        "overlaps": cov["overlaps"]})
            now = time.monotonic() if now is None else now
            for m, st in sorted(members.items()):
                if st["closed"]:
                    continue
                if st["assigns"] == 0:
                    stuck.append({"member": m, "reason": "never_assigned"})
                elif now - st["last_poll"] > stuck_after_s:
                    stuck.append({"member": m, "reason": "stopped_polling",
                                  "stale_s": round(now - st["last_poll"],
                                                   2)})
            violations["unconverged"] = unconverged
            violations["stuck_consumer"] = stuck
            group_blob = {
                "members": len(members),
                "live": sum(1 for st in members.values()
                            if not st["closed"]),
                "departed": sum(1 for st in members.values()
                                if st["closed"]),
                "assignments": sum(st["assigns"]
                                   for st in members.values()),
                "converged_s": converged_s,
                "coverage": cov,
            }

        ok = not any(violations.values())
        report = {
            "ok": ok,
            "acked": len(acked), "consumed": len(consumed),
            "failed_produces": len(failed),
            "txns": {"committed":
                     sum(1 for s in txns.values() if s == "committed"),
                     "aborted":
                     sum(1 for s in txns.values() if s == "aborted"),
                     "unknown":
                     sum(1 for s in txns.values() if s == "unknown"),
                     "open":
                     sum(1 for s in txns.values() if s == "open")},
            "violations": {k: v[:REPORT_ROW_CAP]
                           for k, v in violations.items()},
        }
        if group_blob is not None:
            report["group"] = group_blob
        if check_continuity:
            report["continuity"] = {
                "windows": len(windows),
                "flow_stall_s": flow_stall_s,
                "tracked_partitions": len(flow),
                "flow_gaps": len(violations.get("flow_gap", ()))}
        if not ok:
            report["diff_path"] = self._dump_diff(violations, report)
            # the trace that explains the storm must survive it: stamp
            # the verdict into the rings, then flight-dump them
            # (flight_record self-checks and returns None when tracing
            # is off, so the key is present either way)
            if trace.enabled:
                trace.instant("chaos", "oracle_violation",
                              {k: len(v) for k, v in violations.items()})
            report["flight_path"] = trace.flight_record("oracle_violation")
            if raise_on_violation:
                raise OracleViolation(report)
        return report

    def _dump_diff(self, violations: dict, report: dict) -> Optional[str]:
        d = self.dump_dir or tempfile.gettempdir()
        path = os.path.join(
            d, f"tk_oracle_{os.getpid()}_{id(self) & 0xFFFF:x}.json")
        try:
            with open(path, "w") as f:
                json.dump({"summary": {k: len(v)
                                       for k, v in violations.items()},
                           "stats": {"acked": report["acked"],
                                     "consumed": report["consumed"],
                                     "txns": report["txns"]},
                           "violations": violations}, f, indent=1,
                          default=_short)
        except OSError:
            return None
        return path


def _short(v) -> str:
    if isinstance(v, (bytes, bytearray)):
        return v[:48].decode("latin1")
    return str(v)[:64]
