"""CLI storm runner: ``python -m librdkafka_tpu.chaos``.

    python -m librdkafka_tpu.chaos --list
    python -m librdkafka_tpu.chaos --scenario rolling_restart_eos --seed 1
    python -m librdkafka_tpu.chaos --fast          # the tier-1 smoke set
    python -m librdkafka_tpu.chaos --all

Exit status 0 iff every requested storm's oracle verdict is clean
(``oracle_selftest`` passes by *detecting* its planted violation).
Reports print as JSON — the ``replay_key`` field plus ``--seed`` is the
replay workflow: same seed, same fault timeline, byte-for-byte.
"""
from __future__ import annotations

import argparse
import json
import sys

from .oracle import OracleViolation
from .scenarios import SCENARIOS


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m librdkafka_tpu.chaos",
        description="chaos storms over the mock cluster")
    ap.add_argument("--scenario", action="append", default=[],
                    help="scenario name (repeatable); see --list")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the scenario's default seed "
                         "(replay-from-seed)")
    ap.add_argument("--fast", action="store_true",
                    help="run the fast (tier-1) scenario set")
    ap.add_argument("--all", action="store_true",
                    help="run every scenario, storms included")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    args = ap.parse_args(argv)

    if args.list:
        for name, (_fn, desc, fast) in SCENARIOS.items():
            tier = "fast" if fast else "slow"
            print(f"{name:32s} [{tier}] {desc}")
        return 0

    names = list(args.scenario)
    if args.all:
        names = list(SCENARIOS)
    elif args.fast:
        names = [n for n, (_f, _d, fast) in SCENARIOS.items() if fast]
    if not names:
        ap.error("pick --scenario NAME, --fast, or --all (see --list)")

    rc = 0
    for name in names:
        if name not in SCENARIOS:
            print(f"unknown scenario {name!r} (see --list)",
                  file=sys.stderr)
            return 2
        fn = SCENARIOS[name][0]
        kwargs = {} if args.seed is None else {"seed": args.seed}
        print(f"== {name} ==", file=sys.stderr)
        try:
            report = fn(**kwargs)
        except OracleViolation as v:
            report = v.report
            rc = 1
        # timeline is valuable but long; keep stderr JSON complete and
        # stdout summary humane
        print(json.dumps(report, indent=1, default=str))
        ok = report.get("ok")
        if name == "oracle_selftest":
            ok = not ok and report.get("diff_path")
        status = "PASS" if ok else "FAIL"
        print(f"== {name}: {status} (acked={report.get('acked')} "
              f"consumed={report.get('consumed')})", file=sys.stderr)
        if not ok:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
