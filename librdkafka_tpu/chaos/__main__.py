"""CLI storm runner: ``python -m librdkafka_tpu.chaos``.

    python -m librdkafka_tpu.chaos --list
    python -m librdkafka_tpu.chaos --scenario external_kill9_eos --seed 21
    python -m librdkafka_tpu.chaos --fast          # the tier-1 smoke set
    python -m librdkafka_tpu.chaos --all           # everything but soak
    python -m librdkafka_tpu.chaos --all --soak    # everything

Exit status 0 iff every requested storm's oracle verdict is clean
(``oracle_selftest`` passes by *detecting* its planted violation).
Reports print as JSON — the ``replay_key`` field plus ``--seed`` is the
replay workflow: same seed, same fault timeline, byte-for-byte (also
against the out-of-process cluster: a fresh supervisor resolves the
same targets).
"""
from __future__ import annotations

import argparse
import json
import sys

from .oracle import OracleViolation
from .scenarios import SCENARIOS


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m librdkafka_tpu.chaos",
        description="chaos storms over the mock cluster (in-process "
                    "and out-of-process tiers)")
    ap.add_argument("--scenario", action="append", default=[],
                    help="scenario name (repeatable); see --list")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the scenario's default seed "
                         "(replay-from-seed)")
    ap.add_argument("--fast", action="store_true",
                    help="run the fast (tier-1) scenario set")
    ap.add_argument("--all", action="store_true",
                    help="run every scenario except the soak tier "
                         "(add --soak to include it)")
    ap.add_argument("--soak", action="store_true",
                    help="include the multi-minute soak storms in "
                         "--all (or run them via --scenario)")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios (name, tier, default seed, "
                         "invariants checked) and exit")
    args = ap.parse_args(argv)

    if args.list:
        print(f"{'scenario':32s} {'tier':5s} {'seed':>5s}  "
              f"invariants checked")
        for name, sc in SCENARIOS.items():
            print(f"{name:32s} {sc.tier:5s} {sc.seed:5d}  "
                  f"{sc.invariants}")
            print(f"{'':32s} {'':5s} {'':5s}  - {sc.desc}")
        return 0

    names = list(args.scenario)
    if args.all:
        names = [n for n, sc in SCENARIOS.items()
                 if sc.tier != "soak" or args.soak]
    elif args.fast:
        names = [n for n, sc in SCENARIOS.items() if sc.tier == "fast"]
    if not names:
        ap.error("pick --scenario NAME, --fast, or --all (see --list)")

    rc = 0
    for name in names:
        if name not in SCENARIOS:
            print(f"unknown scenario {name!r} (see --list)",
                  file=sys.stderr)
            return 2
        fn = SCENARIOS[name].fn
        kwargs = {} if args.seed is None else {"seed": args.seed}
        print(f"== {name} ==", file=sys.stderr)
        try:
            report = fn(**kwargs)
        except OracleViolation as v:
            report = v.report
            rc = 1
        # timeline is valuable but long; keep stderr JSON complete and
        # stdout summary humane
        print(json.dumps(report, indent=1, default=str))
        ok = report.get("ok")
        if name == "oracle_selftest":
            ok = not ok and report.get("diff_path")
        status = "PASS" if ok else "FAIL"
        print(f"== {name}: {status} (acked={report.get('acked')} "
              f"consumed={report.get('consumed')})", file=sys.stderr)
        if not ok:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
