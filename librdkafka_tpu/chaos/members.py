"""Thread-cheap consumer-group members: hundreds-to-1000 in-process
group members multiplexed over a handful of threads (ISSUE 12).

A real ``Consumer`` costs threads (per-broker IO, timers) — fine for a
dozen members, fatal for a thousand.  ``LiteMemberFleet`` keeps each
member as a tiny FSM record (join → sync → heartbeat/fetch, the
rd_kafka_cgrp join FSM distilled) and drives N of them from a few
worker threads, each owning ONE nonblocking TCP connection per broker
(group requests are keyed by member_id in the body, so members share
connections freely — the broker doesn't care).  This is what scales
the PR 9 churn storms from tens to 1000 members.

The fleet speaks the real wire protocol (protocol/apis.py schemas) to
the mock cluster — in-process ``MockCluster`` or the out-of-process
supervised rig (``ClusterHandle``), where the coordinator can be
SIGKILLed mid-rebalance.  Both rebalance protocols are implemented:

* ``cooperative-sticky`` (KIP-429): owned partitions ride the
  Subscription v1 metadata, sync deltas apply incrementally, and
  unrevoked partitions KEEP FETCHING through the whole rebalance —
  the zero stop-the-world property the oracle's continuity invariant
  (``check_continuity``) asserts.
* ``range`` (EAGER): every rejoin revokes the world first — the
  baseline the ``bench.py --rebalance`` leg measures cooperative
  against.

Members "consume" for real: each owner issues Fetch v4 to the
partition leader, parses the v2 batches, and records values + stamps
into the shared :class:`~.oracle.DeliveryOracle`.  Ownership handoffs
resume from a fleet-level position book (the commit analog), so the
storm stays at-least-once, and every partition's covered/uncovered
time is accounted (``partition_unavailability()`` — the
stop-the-world seconds the bench leg compares).

Determinism: all randomness (churn stagger jitter) draws from
``random.Random(seed)``; the chaos schedule owns the fault timeline,
so same seed ⇒ same ``replay_key`` (the PR 9 contract).
"""
from __future__ import annotations

import random
import select
import socket
import struct
import threading
import time
from typing import Callable, Optional

from ..analysis.locks import new_lock
from ..analysis.races import shared_dict, shared_list
from ..client.assignor import (ASSIGNOR_PROTOCOLS, ASSIGNORS,
                               assignment_decode, assignment_encode,
                               subscription_decode, subscription_encode)
from ..client.errors import Err
from ..protocol import msgset
from ..protocol.apis import build_request, parse_response
from ..protocol.proto import ApiKey
from .oracle import DeliveryOracle

#: fetch request knobs: tiny waits keep one connection serving many
#: members without head-of-line blocking
_FETCH_MAX_WAIT_MS = 60
_FETCH_MAX_BYTES = 262144


class _Conn:
    """One nonblocking client connection to one broker: framed request
    send + response dispatch by correlation id.  Owned by exactly one
    worker thread — no locking; a dead connection fails its in-flight
    callbacks and is reconnected lazily with backoff."""

    def __init__(self, addr: tuple[str, int]):
        self.addr = addr
        self.sock: Optional[socket.socket] = None
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.inflight: dict[int, tuple[ApiKey, Optional[int], Callable]] = {}
        self.corrid = 0
        self.next_connect = 0.0     # backoff gate after a failure

    def alive(self) -> bool:
        return self.sock is not None

    def connect(self, now: float) -> bool:
        if self.sock is not None:
            return True
        if now < self.next_connect:
            return False
        try:
            s = socket.create_connection(self.addr, timeout=0.4)
        except OSError:
            self.next_connect = now + 0.25
            return False
        s.setblocking(False)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock = s
        return True

    def close(self, err: Optional[Exception] = None):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        self.sock = None
        self.rbuf.clear()
        self.wbuf.clear()
        self.next_connect = time.monotonic() + 0.25
        pending = list(self.inflight.values())
        self.inflight.clear()
        e = err or ConnectionError("connection lost")
        for _api, _ver, cb in pending:
            cb(e, None)

    def send(self, api: ApiKey, body: dict, cb: Callable,
             version: Optional[int] = None) -> bool:
        """Queue one request; ``cb(err, resp)`` ALWAYS fires — from
        ``pump`` on response, from ``close`` on connection death, or
        synchronously here when the connection is already gone (so FSM
        ``pending`` flags can never wedge)."""
        if self.sock is None:
            cb(ConnectionError("not connected"), None)
            return False
        self.corrid += 1
        corrid = self.corrid
        self.wbuf += build_request(api, corrid, "lite-member", body,
                                   version=version)
        self.inflight[corrid] = (api, version, cb)
        self._flush()
        return self.sock is not None

    def _flush(self):
        if self.sock is None or not self.wbuf:
            return
        try:
            n = self.sock.send(self.wbuf)
            del self.wbuf[:n]
        except BlockingIOError:
            pass
        except OSError as e:
            self.close(e)

    def pump(self):
        """Read whatever is available and dispatch complete frames."""
        if self.sock is None:
            return
        self._flush()
        try:
            while True:
                chunk = self.sock.recv(262144)
                if not chunk:
                    self.close()
                    return
                self.rbuf += chunk
        except BlockingIOError:
            pass
        except OSError as e:
            self.close(e)
            return
        while len(self.rbuf) >= 4:
            size = struct.unpack_from(">i", self.rbuf)[0]
            if len(self.rbuf) < 4 + size:
                break
            frame = bytes(self.rbuf[4:4 + size])
            del self.rbuf[:4 + size]
            corrid = struct.unpack_from(">i", frame)[0]
            entry = self.inflight.pop(corrid, None)
            if entry is None:
                continue
            api, ver, cb = entry
            try:
                _corr, body = parse_response(api, frame, version=ver)
            except Exception as e:   # malformed frame: fail this call
                cb(e, None)
                continue
            cb(None, body)


class _Member:
    """One group member's FSM record (single worker thread owns it)."""

    __slots__ = ("name", "member_id", "generation", "state", "owned",
                 "protocol", "start_at", "leave_at", "hb_due",
                 "fetch_due", "pending", "closed", "static_id", "rebal")

    def __init__(self, name: str, start_at: float,
                 leave_at: Optional[float],
                 static_id: Optional[str] = None):
        self.name = name
        self.member_id = ""
        self.generation = -1
        self.state = "wait"       # wait/init/stable/done
        self.owned: dict[tuple[str, int], int] = {}   # tp -> next offset
        self.protocol = ""
        self.start_at = start_at
        self.leave_at = leave_at
        self.hb_due = 0.0
        self.fetch_due = 0.0
        self.pending = False      # one group request in flight at a time
        self.closed = False
        self.static_id = static_id
        self.rebal = False        # contributing to the rebalancing gauge


class LiteMemberFleet:
    """Drive ``members`` thread-cheap group members against a cluster.

    Cross-thread state is declared to the lockset detector and guarded
    by the ``chaos.members`` factory lock: the position book, the
    leader map, the coordinator cache, the per-partition coverage
    ledger and the rebalancing-interval book are all shared between
    worker threads (and read by the storm thread after ``stop()``)."""

    def __init__(self, bootstrap: str, *, group_id: str, topic: str,
                 partitions: int, members: int, oracle: DeliveryOracle,
                 seed: int, strategy: str = "cooperative-sticky",
                 threads: int = 4, heartbeat_s: float = 0.4,
                 session_ms: int = 6000, rebalance_ms: int = 3000,
                 fetch: bool = True,
                 churn_members: int = 0, churn_start_s: float = 1.0,
                 churn_period_s: float = 0.2,
                 churn_lifetime_s: float = 2.5,
                 member_stagger_s: float = 0.0):
        self.bootstrap = [(h, int(p)) for h, p in
                          (hp.rsplit(":", 1)
                           for hp in bootstrap.split(","))]
        self.group_id = group_id
        self.topic = topic
        self.partitions = partitions
        self.oracle = oracle
        self.strategy = strategy
        self.proto = ASSIGNOR_PROTOCOLS.get(strategy, "EAGER")
        self.heartbeat_s = heartbeat_s
        self.session_ms = session_ms
        self.rebalance_ms = rebalance_ms
        self.fetch = fetch
        self.errors: list[str] = shared_list("members.errors")
        self._lock = new_lock("chaos.members")
        rng = random.Random(seed)
        now0 = 0.0   # member clocks are offsets from start()
        self._members: list[_Member] = []
        for i in range(members):
            self._members.append(_Member(
                f"m{i:04d}", now0 + i * member_stagger_s, None))
        for j in range(churn_members):
            start = (churn_start_s + j * churn_period_s
                     + rng.random() * 0.1)
            self._members.append(_Member(
                f"x{j:04d}", start, start + churn_lifetime_s))
        self.n_threads = max(1, min(threads, members + churn_members))
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # ---- fleet-shared books (all under chaos.members) ----
        # group position book: the commit analog ownership handoffs
        # resume from — (t, p) -> next fetch offset
        self.positions: dict[tuple, int] = shared_dict("members.positions")
        # partition -> leader broker id (Metadata-refreshed on error)
        self.leaders: dict[tuple, int] = shared_dict("members.leaders")
        # broker id -> (host, port) advertised addresses
        self.broker_addrs: dict[int, tuple] = shared_dict(
            "members.broker_addrs")
        self.coordinator: Optional[int] = None
        # per-partition coverage ledger: (t,p) -> active fetcher count,
        # plus (ts, tp, delta) events — partition_unavailability()
        # integrates the zero-fetcher time (eager's stop-the-world)
        self._active: dict[tuple, int] = shared_dict("members.active")
        self._cov_events: list[tuple] = shared_list("members.cov_events")
        # group-wide rebalance intervals: [start, end) spans where >=1
        # member was mid-rejoin — bench's "messages flowing DURING the
        # rebalance" denominator
        self._rebalancing = 0
        self._reb_events: list[tuple] = shared_list("members.reb_events")
        self._t0 = 0.0
        self._metadata_due = 0.0

    # ------------------------------------------------------- lifecycle --
    def start(self):
        self._t0 = time.monotonic()
        per = [[] for _ in range(self.n_threads)]
        for i, m in enumerate(self._members):
            per[i % self.n_threads].append(m)
        for i, group in enumerate(per):
            th = threading.Thread(target=self._worker, args=(i, group),
                                  name=f"lite-members-{i}", daemon=True)
            th.start()
            self._threads.append(th)

    def stop(self, timeout: float = 20.0):
        self._stop.set()
        for th in self._threads:
            th.join(timeout)

    def live_member_count(self) -> int:
        return sum(1 for m in self._members
                   if not m.closed and m.state != "wait")

    # ----------------------------------------------------- shared books --
    def _mark_rebalancing(self, delta: int):
        now = time.monotonic()
        with self._lock:
            was = self._rebalancing
            self._rebalancing += delta
            if was == 0 and self._rebalancing > 0:
                self._reb_events.append((now, 1))
            elif was > 0 and self._rebalancing == 0:
                self._reb_events.append((now, 0))

    def _flow_start(self, tp: tuple):
        now = time.monotonic()
        with self._lock:
            n = self._active.get(tp, 0)
            self._active[tp] = n + 1
            if n == 0:
                self._cov_events.append((now, tp, 1))

    def _flow_stop(self, tp: tuple):
        now = time.monotonic()
        with self._lock:
            n = self._active.get(tp, 0) - 1
            self._active[tp] = n if n > 0 else 0
            if n <= 0:
                self._cov_events.append((now, tp, 0))

    def partition_unavailability(self, until: Optional[float] = None
                                 ) -> dict:
        """Integrate each partition's zero-active-fetcher time from the
        first moment it was covered (so the initial join ramp doesn't
        count) until ``until``/now.  Returns per-partition seconds +
        the fleet total — eager's stop-the-world shows up here; the
        cooperative total must stay a small fraction of it."""
        end = until if until is not None else time.monotonic()
        with self._lock:
            events = list(self._cov_events)
        per: dict[tuple, float] = {}
        state: dict[tuple, tuple] = {}   # tp -> (covered?, since)
        for ts, tp, up in events:
            cov, since = state.get(tp, (None, None))
            if cov is None:
                state[tp] = (bool(up), ts)
                continue
            if cov and not up:
                state[tp] = (False, ts)
            elif not cov and up:
                per[tp] = per.get(tp, 0.0) + (ts - since)
                state[tp] = (True, ts)
        for tp, (cov, since) in state.items():
            if cov is False:
                per[tp] = per.get(tp, 0.0) + (max(0.0, end - since))
        total = round(sum(per.values()), 3)
        return {"total_s": total,
                "per_partition_s": {f"{t}:{p}": round(v, 3)
                                    for (t, p), v in sorted(per.items())}}

    def rebalancing_intervals(self, until: Optional[float] = None
                              ) -> list[tuple[float, float]]:
        """Closed [start, end] spans where the group was rebalancing
        (>=1 member mid-rejoin)."""
        end_t = until if until is not None else time.monotonic()
        with self._lock:
            ev = list(self._reb_events)
        out = []
        start = None
        for ts, up in ev:
            if up and start is None:
                start = ts
            elif not up and start is not None:
                out.append((start, ts))
                start = None
        if start is not None:
            out.append((start, end_t))
        return out

    # ------------------------------------------------------ worker loop --
    def _worker(self, idx: int, members: list[_Member]):
        # TWO conns per broker: group requests (JoinGroup parks on the
        # coordinator for up to the whole rebalance window) and fetches
        # ride separate sockets, or a mass rejoin head-of-line-blocks
        # every fetch to the coordinator broker for seconds — a
        # self-inflicted flow gap the continuity invariant caught
        conns: dict[int, _Conn] = {}        # broker id -> group conn
        fconns: dict[int, _Conn] = {}       # broker id -> fetch conn
        boot = _Conn(self.bootstrap[idx % len(self.bootstrap)])
        try:
            while not self._stop.is_set():
                now = time.monotonic()
                self._serve_metadata(boot, conns, now)
                socks = {c.sock: c for c in
                         list(conns.values()) + list(fconns.values())
                         + [boot]
                         if c.sock is not None}
                if socks:
                    try:
                        r, _w, _x = select.select(list(socks), [], [],
                                                  0.01)
                    except (OSError, ValueError):
                        r = []
                    for s in r:
                        socks[s].pump()
                else:
                    self._stop.wait(0.02)
                for m in members:
                    self._serve_member(m, conns, fconns, now)
            # deliberate departure on stop: churners already closed;
            # remaining members just stop (the storm freezes its group
            # verdict before calling stop(), like Storm teardown)
        except Exception as e:   # worker must never die silently
            with self._lock:
                self.errors.append(f"worker-{idx}: {e!r}")
        finally:
            for c in list(conns.values()) + list(fconns.values()) + [boot]:
                if c.sock is not None:
                    c.close()

    # -------------------------------------------------------- metadata --
    def _serve_metadata(self, boot: _Conn, conns: dict, now: float):
        """Keep the leader map + coordinator cache warm (one worker's
        bootstrap conn refreshes for everyone; staleness is healed on
        NOT_LEADER/NOT_COORDINATOR errors)."""
        with self._lock:
            due = now >= self._metadata_due
            if due:
                self._metadata_due = now + 0.5
        if not due:
            return
        if not boot.connect(now) or boot.inflight:
            return

        def on_meta(err, resp):
            if err is not None or resp is None:
                return
            with self._lock:
                for b in resp.get("brokers", ()):
                    self.broker_addrs[b["node_id"]] = (b["host"],
                                                       b["port"])
                for t in resp.get("topics", ()):
                    if t["topic"] != self.topic:
                        continue
                    for p in t["partitions"]:
                        if p["leader"] >= 0:
                            self.leaders[(t["topic"], p["partition"])] = \
                                p["leader"]

        boot.send(ApiKey.Metadata, {"topics": [self.topic],
                                    "allow_auto_topic_creation": True},
                  on_meta)

        def on_coord(err, resp):
            if err is not None or resp is None:
                return
            if resp.get("error_code", -1) == 0:
                with self._lock:
                    self.coordinator = resp["node_id"]
                    self.broker_addrs[resp["node_id"]] = (resp["host"],
                                                          resp["port"])

        boot.send(ApiKey.FindCoordinator,
                  {"key": self.group_id, "key_type": 0}, on_coord)

    def _conn_to(self, broker_id: Optional[int], conns: dict,
                 now: float) -> Optional[_Conn]:
        if broker_id is None:
            return None
        with self._lock:
            addr = self.broker_addrs.get(broker_id)
        if addr is None:
            return None
        c = conns.get(broker_id)
        if c is None or c.addr != tuple(addr):
            if c is not None and c.sock is not None:
                c.close()
            c = conns[broker_id] = _Conn(tuple(addr))
        if not c.connect(now):
            return None
        return c

    def _enter_rebalance(self, m: _Member):
        if not m.rebal:
            m.rebal = True
            self._mark_rebalancing(1)
            # continuity window: kept partitions must flow from HERE
            # until the next assignment lands
            self.oracle.record_rebalance_begin(m.name)

    def _exit_rebalance(self, m: _Member):
        if m.rebal:
            m.rebal = False
            self._mark_rebalancing(-1)

    # ------------------------------------------------------- member FSM --
    def _serve_member(self, m: _Member, conns: dict, fconns: dict,
                      now: float):
        rel = now - self._t0
        if m.state == "done":
            return
        if m.state == "wait":
            if rel < m.start_at:
                return
            m.state = "init"
            self._enter_rebalance(m)
        if m.leave_at is not None and rel >= m.leave_at:
            self._leave(m, conns, now)
            return
        # owned partitions keep fetching in EVERY state — through the
        # whole join/sync round trip: the cooperative zero
        # stop-the-world property (eager members own nothing here,
        # their world was revoked at rejoin)
        if self.fetch and m.owned and now >= m.fetch_due:
            self._fetch(m, fconns, now)
        if m.pending:
            return
        if m.state == "init":
            self._join(m, conns, now)
        elif m.state == "stable" and now >= m.hb_due:
            self._heartbeat(m, conns, now)

    def _coord_conn(self, conns: dict, now: float) -> Optional[_Conn]:
        with self._lock:
            coord = self.coordinator
        return self._conn_to(coord, conns, now)

    def _join(self, m: _Member, conns: dict, now: float):
        c = self._coord_conn(conns, now)
        if c is None:
            return
        owned_d: dict[str, list] = {}
        if self.proto == "COOPERATIVE":
            for (t, p) in m.owned:
                owned_d.setdefault(t, []).append(p)
            meta = subscription_encode([self.topic], owned=owned_d)
        else:
            meta = subscription_encode([self.topic])
        m.pending = True

        def on_join(err, resp):
            m.pending = False
            if err is not None or resp is None:
                return                      # retried next serve pass
            ec = Err.from_wire(resp["error_code"])
            if ec in (Err.UNKNOWN_MEMBER_ID, Err.ILLEGAL_GENERATION):
                self._lost(m, "join:" + ec.name)
                m.member_id = ""
                return
            if ec in (Err.NOT_COORDINATOR,
                      Err.COORDINATOR_NOT_AVAILABLE):
                with self._lock:
                    self.coordinator = None
                return
            if ec != Err.NO_ERROR:
                return
            m.member_id = resp["member_id"]
            m.generation = resp["generation_id"]
            m.protocol = resp["protocol"]
            assignments = []
            if resp["leader_id"] == m.member_id:
                assignments = self._lead(resp["members"])
            self._sync(m, conns, assignments)

        c.send(ApiKey.JoinGroup, {
            "group_id": self.group_id,
            "session_timeout": self.session_ms,
            "rebalance_timeout": self.rebalance_ms,
            "member_id": m.member_id,
            "group_instance_id": m.static_id,
            "protocol_type": "consumer",
            "protocols": [{"name": self.strategy, "metadata": meta}]},
            on_join)

    def _lead(self, members_meta: list[dict]) -> list[dict]:
        """Leader-side assignment over the joined members' metadata."""
        subs, owned = {}, {}
        for row in members_meta:
            d = subscription_decode(row["metadata"])
            subs[row["member_id"]] = d["topics"]
            owned[row["member_id"]] = d.get("owned_partitions") or {}
        parts = {self.topic: self.partitions}
        fn = ASSIGNORS[self.strategy]
        if self.proto == "COOPERATIVE":
            per = fn(subs, parts, owned)
        else:
            per = fn(subs, parts)
        return [{"member_id": mid, "assignment": assignment_encode(a)}
                for mid, a in per.items()]

    def _sync(self, m: _Member, conns: dict, assignments: list[dict]):
        c = self._coord_conn(conns, time.monotonic())
        if c is None:
            return                         # rejoin next pass
        m.pending = True

        def on_sync(err, resp):
            m.pending = False
            if err is not None or resp is None:
                return
            ec = Err.from_wire(resp["error_code"])
            if ec == Err.REBALANCE_IN_PROGRESS:
                self._rejoin(m)
                return
            if ec in (Err.UNKNOWN_MEMBER_ID, Err.ILLEGAL_GENERATION):
                self._lost(m, "sync:" + ec.name)
                if ec == Err.UNKNOWN_MEMBER_ID:
                    m.member_id = ""
                return
            if ec != Err.NO_ERROR:
                return
            target = assignment_decode(resp["assignment"] or b"")
            self._apply(m, target)

        c.send(ApiKey.SyncGroup, {
            "group_id": self.group_id, "generation_id": m.generation,
            "member_id": m.member_id, "assignments": assignments},
            on_sync)

    def _apply(self, m: _Member, target: dict):
        tgt = {(t, p) for t, ps in target.items() for p in ps}
        own = set(m.owned)
        if self.proto == "COOPERATIVE":
            revoked = own - tgt
            added = tgt - own
            if revoked:
                self.oracle.record_revoke(m.name, sorted(revoked))
                for tp in sorted(revoked):
                    self._retire(m, tp)
            self.oracle.record_assign(m.name, sorted(added),
                                      incremental=True)
            for tp in sorted(added):
                self._adopt(m, tp)
            m.state = "stable"
            m.hb_due = time.monotonic() + self.heartbeat_s
            self._exit_rebalance(m)
            if revoked:
                self._rejoin(m)     # freed partitions land next gen
        else:
            # EAGER: the world was revoked at rejoin; everything in the
            # target is a fresh adoption
            self.oracle.record_assign(m.name, sorted(tgt))
            for tp in sorted(tgt):
                self._adopt(m, tp)
            m.state = "stable"
            m.hb_due = time.monotonic() + self.heartbeat_s
            self._exit_rebalance(m)

    def _adopt(self, m: _Member, tp: tuple):
        with self._lock:
            pos = self.positions.get(tp, 0)
        m.owned[tp] = pos
        self._flow_start(tp)

    def _retire(self, m: _Member, tp: tuple):
        pos = m.owned.pop(tp, None)
        if pos is not None:
            with self._lock:
                if pos > self.positions.get(tp, 0):
                    self.positions[tp] = pos
            self._flow_stop(tp)

    def _rejoin(self, m: _Member):
        """Trigger a new join round.  EAGER revokes everything first
        (the stop-the-world the continuity invariant outlaws for
        cooperative members)."""
        self._enter_rebalance(m)
        if self.proto != "COOPERATIVE" and m.owned:
            self.oracle.record_revoke(m.name)       # full revoke
            for tp in sorted(m.owned):
                self._retire(m, tp)
        m.state = "init"

    def _lost(self, m: _Member, why: str):
        """Fenced/unknown: ownership is void regardless of protocol."""
        self._enter_rebalance(m)
        if m.owned:
            self.oracle.record_revoke(
                m.name, sorted(m.owned)
                if self.proto == "COOPERATIVE" else None)
            for tp in sorted(m.owned):
                self._retire(m, tp)
        m.generation = -1
        m.state = "init"

    def _heartbeat(self, m: _Member, conns: dict, now: float):
        c = self._coord_conn(conns, now)
        if c is None:
            return
        m.hb_due = now + self.heartbeat_s
        self.oracle.record_poll(m.name)
        m.pending = True

        def on_hb(err, resp):
            m.pending = False
            if err is not None or resp is None:
                return
            ec = Err.from_wire(resp["error_code"])
            if ec == Err.NO_ERROR:
                return
            if ec == Err.REBALANCE_IN_PROGRESS:
                self._rejoin(m)
            elif ec in (Err.UNKNOWN_MEMBER_ID, Err.ILLEGAL_GENERATION):
                self._lost(m, "hb:" + ec.name)
                if ec == Err.UNKNOWN_MEMBER_ID:
                    m.member_id = ""
            elif ec in (Err.NOT_COORDINATOR,
                        Err.COORDINATOR_NOT_AVAILABLE):
                with self._lock:
                    self.coordinator = None

        c.send(ApiKey.Heartbeat, {
            "group_id": self.group_id, "generation_id": m.generation,
            "member_id": m.member_id}, on_hb)

    def _leave(self, m: _Member, conns: dict, now: float):
        for tp in sorted(m.owned):
            self._retire(m, tp)
        self._exit_rebalance(m)
        m.state = "done"
        m.closed = True
        self.oracle.record_member_closed(m.name)
        c = self._coord_conn(conns, now)
        if c is not None and m.member_id:
            c.send(ApiKey.LeaveGroup, {"group_id": self.group_id,
                                       "member_id": m.member_id},
                   lambda e, r: None)

    # ----------------------------------------------------------- fetch --
    def _fetch(self, m: _Member, conns: dict, now: float):
        """One fetch round: owned partitions grouped by leader; the
        member keeps consuming THROUGH rebalances (cooperative) — this
        is the flow the continuity invariant measures."""
        m.fetch_due = now + 0.05
        by_leader: dict[int, list] = {}
        with self._lock:
            for tp, pos in m.owned.items():
                leader = self.leaders.get(tp)
                if leader is not None:
                    by_leader.setdefault(leader, []).append((tp, pos))
        for leader, tps in by_leader.items():
            c = self._conn_to(leader, conns, now)
            if c is None or len(c.inflight) > 8:
                continue
            per_topic: dict[str, list] = {}
            for (t, p), pos in tps:
                per_topic.setdefault(t, []).append(
                    {"partition": p, "fetch_offset": pos,
                     "max_bytes": _FETCH_MAX_BYTES // 4})
            body = {"replica_id": -1,
                    "max_wait_time": _FETCH_MAX_WAIT_MS,
                    "min_bytes": 1, "max_bytes": _FETCH_MAX_BYTES,
                    "isolation_level": 0,
                    "topics": [{"topic": t, "partitions": rows}
                               for t, rows in per_topic.items()]}
            c.send(ApiKey.Fetch, body,
                   self._make_fetch_cb(m), version=4)

    def _make_fetch_cb(self, m: _Member):
        def on_fetch(err, resp):
            if err is not None or resp is None:
                return
            rows = []
            for t in resp.get("topics", ()):
                for p in t.get("partitions", ()):
                    tp = (t["topic"], p["partition"])
                    if tp not in m.owned:
                        continue        # revoked while in flight: drop
                    ec = Err.from_wire(p["error_code"])
                    if ec == Err.NOT_LEADER_FOR_PARTITION:
                        with self._lock:
                            self.leaders.pop(tp, None)
                            self._metadata_due = 0.0
                        continue
                    if ec == Err.OFFSET_OUT_OF_RANGE:
                        m.owned[tp] = 0     # earliest (retention reset)
                        continue
                    if ec != Err.NO_ERROR or not p["records"]:
                        continue
                    pos = m.owned[tp]
                    now = time.monotonic()
                    for info, payload, _full in msgset.iter_batches(
                            p["records"]):
                        if info.codec or info.is_control:
                            # harness scope: uncompressed data batches
                            # (scenario producers run codec=none)
                            pos = max(pos, info.base_offset
                                      + info.record_count)
                            continue
                        for rec in msgset.parse_records_v2(
                                info, payload):
                            if rec.offset < m.owned[tp]:
                                continue        # already seen
                            rows.append((tp[0], tp[1], rec.offset,
                                         rec.value, now))
                            pos = max(pos, rec.offset + 1)
                    m.owned[tp] = pos
                    with self._lock:
                        if pos > self.positions.get(tp, 0):
                            self.positions[tp] = pos
            if rows:
                self.oracle.record_consumed_rows(rows)
        return on_fetch
