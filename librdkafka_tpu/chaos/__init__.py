"""Chaos-engineering subsystem (ISSUE 7): scripted fault schedules,
a delivery-invariant oracle, and a scenario library over the real-TCP
mock cluster.  See CHAOS.md for the DSL reference, oracle invariants
and the replay-from-seed workflow.

    from librdkafka_tpu.chaos import (Schedule, ChaosScheduler,
                                      DeliveryOracle, broker_kill, ...)

CLI: ``python -m librdkafka_tpu.chaos --list``.
"""
from .oracle import DeliveryOracle, OracleViolation
from .schedule import (Action, ChaosContext, ChaosScheduler, Schedule,
                       broker_kill, broker_restart, call, conn_kill,
                       leader_migrate, net, proc_cont, proc_kill9,
                       proc_pause, proc_restart)
from .scenarios import SCENARIOS, Scenario, Storm

__all__ = [
    "Action", "ChaosContext", "ChaosScheduler", "Schedule",
    "broker_kill", "broker_restart", "call", "conn_kill",
    "leader_migrate", "net",
    "proc_kill9", "proc_pause", "proc_cont", "proc_restart",
    "DeliveryOracle", "OracleViolation",
    "SCENARIOS", "Scenario", "Storm",
]
