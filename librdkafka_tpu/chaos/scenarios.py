"""Scenario library: canned chaos storms over the real-TCP mock
cluster, each returning a structured report (oracle verdict + fault
timeline + replay key).

Run via ``python -m librdkafka_tpu.chaos`` (``--list`` to enumerate),
``bench.py --chaos`` (the fast legs as a smoke gate), or the pytest
tier in tests/test_0127_chaos.py (fast scenarios in tier-1, full storms
``slow``-marked behind ``scripts/chaos.sh``).

Every scenario is deterministic from its seed: the fault timeline's
``replay_key`` is identical across runs (schedule.py's contract), so a
failing storm is re-run with the same seed and the same faults fire in
the same order against the same targets.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..client.consumer import Consumer
from ..client.errors import KafkaException
from ..client.producer import Producer
from ..mock.cluster import MockCluster
from ..mock.sockem import Sockem
from ..obs import trace
from .oracle import DeliveryOracle, OracleViolation
from .schedule import (ChaosScheduler, Schedule, broker_kill,
                       broker_restart, conn_kill, leader_migrate, net)


# ---------------------------------------------------------------- storm --
class Storm:
    """One storm run: cluster + optional sockem + oracle + scheduler +
    paced producer/consumer loops.  Scenarios configure and run it;
    everything tears down in ``finally`` so a failed storm never leaks
    threads into the next one (the conftest fixtures police this)."""

    def __init__(self, *, seed: int, brokers: int = 3,
                 partitions: int = 4, topic: str = "chaos",
                 use_sockem: bool = False, min_alive: int = 1,
                 transactional: bool = False, txn_size: int = 5,
                 abort_every: int = 0, isolation: str = "read_committed",
                 consumers: int = 1, consumer_start_delays=(0.0,),
                 duration_s: float = 3.0, pace_ms: float = 4.0,
                 drain_s: float = 20.0,
                 check_duplicates: bool = True, check_order: bool = True,
                 producer_conf: Optional[dict] = None):
        self.seed = seed
        self.topic = topic
        self.partitions = partitions
        self.transactional = transactional
        self.txn_size = txn_size
        self.abort_every = abort_every
        self.isolation = isolation
        self.n_consumers = consumers
        self.consumer_start_delays = consumer_start_delays
        self.duration_s = duration_s
        self.pace_ms = pace_ms
        self.drain_s = drain_s
        self.check_duplicates = check_duplicates
        self.check_order = check_order
        self.producer_conf = producer_conf or {}

        self.cluster = MockCluster(num_brokers=brokers,
                                   topics={topic: partitions})
        self.sockem = Sockem() if use_sockem else None
        self.oracle = DeliveryOracle()
        self.chaos = ChaosScheduler(self.cluster, self.sockem,
                                    min_alive=min_alive)
        self.produced = 0
        self.errors: list[str] = []
        self._stop_consumers = threading.Event()

    # -- client builders --------------------------------------------------
    def _conf(self, extra: dict) -> dict:
        conf = {"bootstrap.servers": self.cluster.bootstrap_servers()}
        if self.sockem is not None:
            conf["connect_cb"] = self.sockem.connect_cb
        conf.update(extra)
        return conf

    def _make_producer(self) -> Producer:
        conf = self._conf({
            "linger.ms": 2,
            "enable.idempotence": True,
            "message.send.max.retries": 1000,
            "retry.backoff.ms": 50,
            "message.timeout.ms": 120000,
            "reconnect.backoff.ms": 50,
        })
        if self.transactional:
            conf["transactional.id"] = f"chaos-tx-{self.seed}"
        conf.update(self.producer_conf)
        return Producer(conf)

    def _make_consumer(self, i: int) -> Consumer:
        return Consumer(self._conf({
            "group.id": f"chaos-g-{self.seed}",
            "client.id": f"chaos-c{i}",
            "auto.offset.reset": "earliest",
            "isolation.level": self.isolation,
            "reconnect.backoff.ms": 50,
        }))

    # -- loops ------------------------------------------------------------
    def _consume_loop(self, i: int, delay: float):
        if delay > 0:
            time.sleep(delay)
        c = self._make_consumer(i)
        try:
            c.subscribe([self.topic])
            while not self._stop_consumers.is_set():
                m = c.poll(0.2)
                if m is not None and m.error is None:
                    self.oracle.record_consumed(m)
        except Exception as e:
            self.errors.append(f"consumer{i}: {e!r}")
        finally:
            c.close()

    def _produce_plain(self, p: Producer, deadline: float):
        seq = 0
        while time.monotonic() < deadline:
            v = b"s%08d" % seq
            try:
                p.produce(self.topic, v, partition=seq % self.partitions,
                          on_delivery=self.oracle.dr())
                seq += 1
            except KafkaException as e:
                if e.error.code.name == "_QUEUE_FULL":
                    p.poll(0.05)
                    continue
                raise
            p.poll(0)
            if self.pace_ms:
                time.sleep(self.pace_ms / 1000.0)
        self.produced = seq

    def _produce_txns(self, p: Producer, deadline: float):
        seq = 0
        tno = 0
        while time.monotonic() < deadline:
            tid = f"txn-{self.seed}-{tno}"
            tno += 1
            want_abort = (self.abort_every
                          and tno % self.abort_every == 0)
            self.oracle.begin_txn(tid)
            try:
                p.begin_transaction()
                for _ in range(self.txn_size):
                    v = b"s%08d" % seq
                    p.produce(self.topic, v,
                              partition=seq % self.partitions,
                              on_delivery=self.oracle.dr(tid))
                    seq += 1
                    p.poll(0)
                if want_abort:
                    p.abort_transaction(60)
                    self.oracle.abort_txn(tid)
                else:
                    p.commit_transaction(60)
                    self.oracle.commit_txn(tid)
            except KafkaException as e:
                # abortable mid-storm error: roll the txn back and keep
                # storming; if even the abort fails the outcome is
                # client-side unknowable — record it as such (the storm
                # asserts this never actually happens)
                self.errors.append(f"txn {tid}: {e!r}")
                try:
                    p.abort_transaction(60)
                    self.oracle.abort_txn(tid)
                except KafkaException as e2:
                    self.errors.append(f"txn {tid} abort: {e2!r}")
                    self.oracle.unknown_txn(tid)
            if self.pace_ms:
                time.sleep(self.pace_ms / 1000.0)
        self.produced = seq

    # -- run --------------------------------------------------------------
    def run(self, schedule: Schedule, *, tamper: Optional[Callable] = None,
            raise_on_violation: bool = True) -> dict:
        trace.enable()        # flight recorder armed for the whole storm
        t0 = time.monotonic()
        consumers = []
        violation: Optional[OracleViolation] = None
        try:
            for i in range(self.n_consumers):
                delay = (self.consumer_start_delays[i]
                         if i < len(self.consumer_start_delays) else 0.0)
                th = threading.Thread(target=self._consume_loop,
                                      args=(i, delay),
                                      name=f"chaos-consumer-{i}",
                                      daemon=True)
                th.start()
                consumers.append(th)

            p = self._make_producer()
            try:
                if self.transactional:
                    p.init_transactions(30)
                self.chaos.start(schedule)
                deadline = time.monotonic() + self.duration_s
                if self.transactional:
                    self._produce_txns(p, deadline)
                else:
                    self._produce_plain(p, deadline)
                self.chaos.join(timeout=schedule.duration + 30)
                self.chaos.heal()
                left = p.flush(60)
                if left:
                    self.errors.append(f"flush left {left} undelivered")
            finally:
                self.chaos.stop()
                p.close()

            # drain: consumers keep polling until every committed ack
            # arrived (or the deadline turns the gap into a loss verdict)
            drain_end = time.monotonic() + self.drain_s
            while (self.oracle.missing_count() > 0
                   and time.monotonic() < drain_end):
                time.sleep(0.2)
            # one extra grace round so trailing duplicates/reorders
            # land in the ledger too, not just the last missing ack
            time.sleep(0.5)
            self._stop_consumers.set()
            for th in consumers:
                th.join(15)

            if tamper is not None:
                tamper(self.oracle)
            try:
                report = self.oracle.verify(
                    check_duplicates=self.check_duplicates,
                    check_order=self.check_order,
                    raise_on_violation=raise_on_violation)
            except OracleViolation as v:
                violation = v
                report = v.report
            report.update({
                "seed": self.seed,
                "produced": self.produced,
                "wall_s": round(time.monotonic() - t0, 2),
                "timeline": self.chaos.timeline,
                "replay_key": self.chaos.replay_key(),
                "schedule_errors": self.chaos.errors,
                "errors": self.errors,
            })
            if violation is not None:
                raise violation
            return report
        finally:
            self._stop_consumers.set()
            for th in consumers:
                th.join(15)
            self.chaos.stop()
            if self.sockem is not None:
                self.sockem.kill_all()
            self.cluster.stop()
            trace.disable()


# ------------------------------------------------------------ scenarios --
def rolling_restart_eos(seed: int = 1, *, kills: int = 5,
                        raise_on_violation: bool = True) -> dict:
    """FLAGSHIP: >=5 rolling broker kill/restarts under sustained
    transactional produce + read_committed consume; the oracle asserts
    zero loss / zero duplication / per-partition order / txn atomicity
    (ISSUE 7 acceptance storm)."""
    interval = 1.2
    storm = Storm(seed=seed, brokers=3, partitions=4, min_alive=2,
                  transactional=True, txn_size=5, abort_every=7,
                  duration_s=1.0 + kills * interval + 0.5, pace_ms=2,
                  drain_s=30.0)
    sched = Schedule(seed=seed)
    for i in range(kills):
        t = 1.0 + i * interval
        sched.at(t, broker_kill("any"))
        sched.at(t + 0.7, broker_restart())    # revive in kill order
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    kills_fired = sum(1 for e in report["timeline"]
                      if e["action"] == "broker_kill"
                      and (e.get("resolved") or {}).get("broker"))
    report["kills_fired"] = kills_fired
    return report


def coordinator_death_midcommit(seed: int = 2, *, rounds: int = 3,
                                raise_on_violation: bool = True) -> dict:
    """Kill the transaction coordinator while commits are in flight;
    the client must FindCoordinator its way to the failover broker and
    the retried EndTxn must stay idempotent (no torn txns)."""
    storm = Storm(seed=seed, brokers=3, partitions=2, min_alive=2,
                  transactional=True, txn_size=4, abort_every=5,
                  duration_s=1.0 + rounds * 2.0, pace_ms=2, drain_s=30.0)
    tid = f"chaos-tx-{seed}"          # Storm._make_producer's txn id
    sched = Schedule(seed=seed)
    for i in range(rounds):
        t = 1.0 + i * 2.0
        sched.at(t, broker_kill(f"coordinator:{tid}"))
        sched.at(t + 1.0, broker_restart())
    return storm.run(sched, raise_on_violation=raise_on_violation)


def leader_migration_midbatch(seed: int = 3, *, migrations: int = 8,
                              raise_on_violation: bool = True) -> dict:
    """Migrate partition leadership every 400 ms while an idempotent
    producer streams batches: every NOT_LEADER redirect must re-route
    without loss, duplication, or reorder."""
    storm = Storm(seed=seed, brokers=3, partitions=4,
                  duration_s=1.0 + migrations * 0.4, pace_ms=2,
                  drain_s=20.0)
    sched = Schedule(seed=seed).every(
        0.8, 0.4, migrations, lambda: leader_migrate("chaos", "any"))
    return storm.run(sched, raise_on_violation=raise_on_violation)


def slow_network_rebalance(seed: int = 4, *,
                           raise_on_violation: bool = True) -> dict:
    """Slow, jittery, briefly half-partitioned network while a second
    consumer joins mid-stream (eager rebalance): plain consumer-group
    semantics are at-least-once, so only zero-loss is asserted —
    duplicates/reorder across the handoff are legal here."""
    storm = Storm(seed=seed, brokers=2, partitions=4, use_sockem=True,
                  consumers=2, consumer_start_delays=(0.0, 1.5),
                  isolation="read_uncommitted",
                  check_duplicates=False, check_order=False,
                  duration_s=4.5, pace_ms=3, drain_s=25.0)
    sched = (Schedule(seed=seed)
             .at(0.5, net(delay_ms=120, jitter_ms=80))
             .at(2.0, net(rx_drop=True))          # half-open partition
             .at(2.6, net(rx_drop=False))
             .at(3.2, conn_kill())
             .at(4.0, net(delay_ms=0, jitter_ms=0)))
    return storm.run(sched, raise_on_violation=raise_on_violation)


def fast_kill_restart(seed: int = 7, *,
                      raise_on_violation: bool = True) -> dict:
    """Tier-1 deterministic smoke (<10 s): one broker kill + restart
    under idempotent produce/consume, full invariant check."""
    storm = Storm(seed=seed, brokers=2, partitions=2, min_alive=1,
                  duration_s=2.2, pace_ms=2, drain_s=15.0)
    sched = (Schedule(seed=seed)
             .at(0.7, broker_kill("any"))
             .at(1.5, broker_restart()))
    return storm.run(sched, raise_on_violation=raise_on_violation)


def fast_net_flap(seed: int = 11, *,
                  raise_on_violation: bool = True) -> dict:
    """Tier-1 deterministic smoke (<10 s): partial writes, latency
    jitter and a mid-flight connection kill via sockem, full invariant
    check on a single-broker cluster."""
    storm = Storm(seed=seed, brokers=1, partitions=2, use_sockem=True,
                  duration_s=2.2, pace_ms=2, drain_s=15.0)
    sched = (Schedule(seed=seed)
             .at(0.3, net(max_write=7))
             .at(0.8, net(delay_ms=80, jitter_ms=40, max_write=0))
             .at(1.3, conn_kill())
             .at(1.7, net(delay_ms=0, jitter_ms=0)))
    return storm.run(sched, raise_on_violation=raise_on_violation)


def oracle_selftest(seed: int = 13) -> dict:
    """Intentionally broken: a quiet run whose ledger is tampered
    (one consumed record dropped = loss; one double-recorded = dup)
    to prove a violation yields an OracleViolation carrying a flight-
    recorder dump + oracle diff. Returns the report (ok=False)."""
    def _tamper(oracle: DeliveryOracle):
        with oracle._lock:
            if len(oracle.consumed) >= 2:
                oracle.consumed.pop()                    # lose one
                oracle.consumed.append(oracle.consumed[0])   # dup one
    storm = Storm(seed=seed, brokers=1, partitions=1,
                  duration_s=0.8, pace_ms=2, drain_s=10.0)
    try:
        storm.run(Schedule(seed=seed), tamper=_tamper)
    except OracleViolation as v:
        return v.report
    raise AssertionError("oracle self-test: tampered ledger was not "
                         "flagged — the oracle is blind")


#: name -> (callable(seed=..), description, runs-in-tier-1)
SCENARIOS = {
    "rolling_restart_eos": (
        rolling_restart_eos,
        "flagship: >=5 rolling broker kill/restarts under EOS "
        "produce + read_committed consume", False),
    "coordinator_death_midcommit": (
        coordinator_death_midcommit,
        "kill the txn coordinator mid-commit; EndTxn retry must stay "
        "idempotent across failover", False),
    "leader_migration_midbatch": (
        leader_migration_midbatch,
        "migrate partition leaders every 400ms under idempotent "
        "produce", False),
    "slow_network_rebalance": (
        slow_network_rebalance,
        "slow/jittery/half-partitioned network during a consumer-group "
        "rebalance (zero-loss)", False),
    "fast_kill_restart": (
        fast_kill_restart,
        "tier-1 smoke: one kill/restart, full invariants, <10s", True),
    "fast_net_flap": (
        fast_net_flap,
        "tier-1 smoke: partial writes + jitter + conn kill, <10s", True),
    "oracle_selftest": (
        oracle_selftest,
        "intentionally broken ledger proves violations dump flight + "
        "diff", True),
}
