"""Scenario library: canned chaos storms over the real-TCP mock
cluster — in-process OR the ISSUE-9 out-of-process tier, where every
broker is a real OS process and faults are real signals.

Run via ``python -m librdkafka_tpu.chaos`` (``--list`` to enumerate),
``bench.py --chaos`` (the fast legs as a smoke gate), or the pytest
tiers (fast scenarios in tier-1, full storms ``slow``-marked behind
``scripts/chaos.sh``, the multi-minute soak behind
``scripts/chaos.sh --soak``).

Every scenario is deterministic from its seed: the fault timeline's
``replay_key`` is identical across runs (schedule.py's contract) —
including against the external cluster, where a fresh supervisor
process must resolve the same targets (coordinator placement hashes
stably, alive-set bookkeeping is handle-local).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, NamedTuple, Optional

from ..analysis.locks import new_lock
from ..client.consumer import Consumer
from ..client.errors import KafkaException
from ..client.producer import Producer
from ..mock.cluster import MockCluster
from ..mock.external import ClusterHandle
from ..mock.sockem import Sockem
from ..obs import trace
from .members import LiteMemberFleet
from .oracle import DeliveryOracle, OracleViolation
from .schedule import (ChaosScheduler, Schedule, broker_kill,
                       broker_restart, conn_kill, leader_migrate, net,
                       proc_cont, proc_kill9, proc_pause, proc_restart)


def _pct(vals: list, q: float) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    return round(s[min(len(s) - 1, int(round(q * (len(s) - 1))))], 1)


def storm_metrics(timeline: list[dict], acked_ts: list[float]
                  ) -> Optional[dict]:
    """Robustness-as-numbers over any fault timeline + ack-stamp
    stream: throughput sustained inside the fault window and
    time-to-first-ack after each kill (the recovery envelope).  Shared
    by Storm (one in-process client) and the fleet driver (many worker
    processes, whose merged stamps arrive unsorted — sorted here)."""
    fired = [e for e in timeline
             if (e.get("resolved") or {}).get("broker") is not None
             and "mono" in e]
    if not fired:
        return None
    acked_ts = sorted(acked_ts)
    t0, t1 = fired[0]["mono"], fired[-1]["mono"]
    window = max(t1 - t0, 1e-3)
    in_window = sum(1 for t in acked_ts if t0 <= t <= t1)
    recovery, unrecovered = [], 0
    kills = [e["mono"] for e in fired
             if e["action"] in ("broker_kill", "proc_kill9")]
    for k in kills:
        nxt = next((t for t in acked_ts if t > k), None)
        if nxt is None:
            unrecovered += 1
        else:
            recovery.append((nxt - k) * 1000.0)
    return {
        "storm_window_s": round(window, 2),
        "storm_acks": in_window,
        "storm_msgs_s": round(in_window / window, 1),
        "kills": len(kills),
        "recovery_ms": {
            "per_kill": [round(r, 1) for r in recovery],
            "p50": _pct(recovery, 0.50),
            "p99": _pct(recovery, 0.99),
            "max": _pct(recovery, 1.0),
            "unrecovered": unrecovered,
        },
    }


# ---------------------------------------------------------------- storm --
class Storm:  # lint: ok shared-state
    """One storm run: cluster (in-process MockCluster or external
    ClusterHandle) + optional sockem + oracle + scheduler + paced
    producer/consumer loops.  Scenarios configure and run it;
    everything tears down in ``finally`` so a failed storm never leaks
    threads — or broker subprocesses — into the next one (the conftest
    fixtures police both).

    shared-state pragma: consumer loops communicate exclusively
    through the oracle's declared ledgers (chaos.oracle lock) and
    threading.Events; the storm thread reads results after joins."""

    def __init__(self, *, seed: int, brokers: int = 3,
                 partitions: int = 4, topic: str = "chaos",
                 external: bool = False,
                 use_sockem: bool = False, min_alive: int = 1,
                 transactional: bool = False, txn_size: int = 5,
                 abort_every: int = 0, isolation: str = "read_committed",
                 consumers: int = 1, consumer_start_delays=(0.0,),
                 check_group: bool = False, converge_s: float = 20.0,
                 strategy: str = "range,roundrobin",
                 check_continuity: bool = False,
                 flow_stall_s: float = 2.0,
                 converge_bound_s: Optional[float] = None,
                 churn_consumers: int = 0, churn_start_s: float = 1.0,
                 churn_period_s: float = 0.5, churn_lifetime_s: float = 2.0,
                 duration_s: float = 3.0, pace_ms: float = 4.0,
                 drain_s: float = 20.0,
                 check_duplicates: bool = True, check_order: bool = True,
                 producer_conf: Optional[dict] = None):
        self.seed = seed
        self.topic = topic
        self.partitions = partitions
        self.external = external
        self.transactional = transactional
        self.txn_size = txn_size
        self.abort_every = abort_every
        self.isolation = isolation
        self.n_consumers = consumers
        self.consumer_start_delays = consumer_start_delays
        self.check_group = check_group
        self.converge_s = converge_s
        self.strategy = strategy
        self.check_continuity = check_continuity
        self.flow_stall_s = flow_stall_s
        self.converge_bound_s = converge_bound_s
        self.churn_consumers = churn_consumers
        self.churn_start_s = churn_start_s
        self.churn_period_s = churn_period_s
        self.churn_lifetime_s = churn_lifetime_s
        self.duration_s = duration_s
        self.pace_ms = pace_ms
        self.drain_s = drain_s
        self.check_duplicates = check_duplicates
        self.check_order = check_order
        self.producer_conf = producer_conf or {}

        if external:
            assert not use_sockem, \
                "sockem shapes the CLIENT socket; pair it with the " \
                "in-process tier (process faults cover the server side)"
            self.cluster = ClusterHandle(brokers=brokers,
                                         topics={topic: partitions})
        else:
            self.cluster = MockCluster(num_brokers=brokers,
                                       topics={topic: partitions})
        self.sockem = Sockem() if use_sockem else None
        self.oracle = DeliveryOracle(track_flow=check_continuity)
        self.chaos = ChaosScheduler(self.cluster, self.sockem,
                                    min_alive=min_alive)
        self.produced = 0
        self.errors: list[str] = []
        self._converged_s: Optional[float] = None
        self._stop_consumers = threading.Event()
        # per-member KIP-227 fetch-session counters, snapshotted just
        # before each consumer closes (ISSUE 14): {member: {broker
        # name: FetchSession.stats()}} — session-chaos scenarios assert
        # renegotiation happened off these
        self.fetch_session_stats: dict = {}

    # -- client builders --------------------------------------------------
    def _conf(self, extra: dict) -> dict:
        conf = {"bootstrap.servers": self.cluster.bootstrap_servers()}
        if self.sockem is not None:
            conf["connect_cb"] = self.sockem.connect_cb
        conf.update(extra)
        return conf

    def _make_producer(self) -> Producer:
        conf = self._conf({
            "linger.ms": 2,
            "enable.idempotence": True,
            "message.send.max.retries": 1000,
            "retry.backoff.ms": 50,
            "message.timeout.ms": 120000,
            "reconnect.backoff.ms": 50,
            # storms kill the same broker many times in a row; the
            # default 10 s backoff ceiling compounds across cycles
            # into multi-second ack wedges (correct client behavior,
            # wrong rig tuning — a chaos rig wants fast re-probing)
            "reconnect.backoff.max.ms": 1000,
        })
        if self.transactional:
            conf["transactional.id"] = f"chaos-tx-{self.seed}"
        conf.update(self.producer_conf)
        return Producer(conf)

    def _make_consumer(self, i: int) -> Consumer:
        conf = {
            "group.id": f"chaos-g-{self.seed}",
            "client.id": f"chaos-c{i}",
            "auto.offset.reset": "earliest",
            "isolation.level": self.isolation,
            "reconnect.backoff.ms": 50,
            "reconnect.backoff.max.ms": 1000,
        }
        if self.check_group:
            # group-heavy storms: heartbeat well inside the mock's
            # rebalance window (3 s) or halves of a churning group keep
            # missing each other's rebalances and the group oscillates
            # between two stable sub-covers instead of converging
            conf["heartbeat.interval.ms"] = 400
            conf["session.timeout.ms"] = 6000
        conf["partition.assignment.strategy"] = self.strategy
        return Consumer(self._conf(conf))

    # -- loops ------------------------------------------------------------
    def _consume_loop(self, i: int, delay: float,
                      lifetime: Optional[float] = None):
        """One group member. ``lifetime`` makes it a churner: it polls
        for that long, then leaves the group deliberately — overlapping
        churner lifetimes ARE the join/leave storm."""
        member = f"c{i}"
        if delay > 0 and self._stop_consumers.wait(delay):
            return
        c = self._make_consumer(i)
        oracle = self.oracle
        try:
            if self.check_group:
                def _on_assign(cons, parts, _m=member):
                    coop = cons.rebalance_protocol() == "COOPERATIVE"
                    oracle.record_assign(
                        _m, [(tp.topic, tp.partition) for tp in parts],
                        incremental=coop)
                    if coop:
                        cons.incremental_assign(parts)
                    else:
                        cons.assign(parts)

                def _on_revoke(cons, parts, _m=member):
                    if cons.rebalance_protocol() == "COOPERATIVE":
                        # KIP-429 incremental revoke: ONLY these leave;
                        # the kept set owes continuity until the next
                        # assignment (oracle window)
                        oracle.record_revoke(
                            _m, [(tp.topic, tp.partition)
                                 for tp in parts])
                        cons.incremental_unassign(parts)
                    else:
                        oracle.record_revoke(_m)
                        cons.unassign()

                c.subscribe([self.topic], on_assign=_on_assign,
                            on_revoke=_on_revoke)
            else:
                c.subscribe([self.topic])
            deadline = (time.monotonic() + lifetime
                        if lifetime is not None else None)
            was_steady = False
            while not self._stop_consumers.is_set():
                if deadline is not None and time.monotonic() >= deadline:
                    break
                m = c.poll(0.2)
                if self.check_group:
                    oracle.record_poll(member)
                    if self.check_continuity:
                        # continuity windows for REAL clients: the join
                        # FSM leaving steady marks rebalance begin (the
                        # kept partitions must flow from here until the
                        # next assignment closes the window)
                        steady = c._rk.cgrp.join_state == "steady"
                        if was_steady and not steady:
                            oracle.record_rebalance_begin(member)
                        was_steady = steady
                if m is not None and m.error is None:
                    oracle.record_consumed(m)
        except Exception as e:
            self.errors.append(f"consumer{i}: {e!r}")
        finally:
            try:
                # snapshot BEFORE close(): close tears sessions down
                # and would count its own resets
                with c._rk._brokers_lock:
                    bs = list(c._rk.brokers.values())
                self.fetch_session_stats[member] = {
                    b.name: b._fetch_session.stats() for b in bs}
            except Exception:
                pass
            if self.check_group and lifetime is not None:
                oracle.record_member_closed(member)
            c.close()

    def _produce_plain(self, p: Producer, deadline: float):
        seq = 0
        while time.monotonic() < deadline:
            v = b"s%08d" % seq
            try:
                p.produce(self.topic, v, partition=seq % self.partitions,
                          on_delivery=self.oracle.dr())
                seq += 1
            except KafkaException as e:
                if e.error.code.name == "_QUEUE_FULL":
                    p.poll(0.05)
                    continue
                raise
            p.poll(0)
            if self.pace_ms:
                time.sleep(self.pace_ms / 1000.0)
        self.produced = seq

    def _produce_txns(self, p: Producer, deadline: float):
        seq = 0
        tno = 0
        while time.monotonic() < deadline:
            tid = f"txn-{self.seed}-{tno}"
            tno += 1
            want_abort = (self.abort_every
                          and tno % self.abort_every == 0)
            self.oracle.begin_txn(tid)
            try:
                p.begin_transaction()
                for _ in range(self.txn_size):
                    v = b"s%08d" % seq
                    p.produce(self.topic, v,
                              partition=seq % self.partitions,
                              on_delivery=self.oracle.dr(tid))
                    seq += 1
                    p.poll(0)
                if want_abort:
                    p.abort_transaction(60)
                    self.oracle.abort_txn(tid)
                else:
                    p.commit_transaction(60)
                    self.oracle.commit_txn(tid)
            except KafkaException as e:
                # abortable mid-storm error: roll the txn back and keep
                # storming; if even the abort fails the outcome is
                # client-side unknowable — record it as such (the storm
                # asserts this never actually happens)
                self.errors.append(f"txn {tid}: {e!r}")
                try:
                    p.abort_transaction(60)
                    self.oracle.abort_txn(tid)
                except KafkaException as e2:
                    self.errors.append(f"txn {tid} abort: {e2!r}")
                    self.oracle.unknown_txn(tid)
            if self.pace_ms:
                time.sleep(self.pace_ms / 1000.0)
        self.produced = seq

    # -- metrics ----------------------------------------------------------
    def _storm_metrics(self, timeline: list[dict]) -> Optional[dict]:
        """Robustness-as-numbers (BENCH_r* trajectory) — the shared
        ``storm_metrics`` over this storm's oracle ack stamps."""
        with self.oracle._lock:
            acked_ts = list(self.oracle.acked_ts)
        return storm_metrics(timeline, acked_ts)

    # -- run --------------------------------------------------------------
    def run(self, schedule: Schedule, *, tamper: Optional[Callable] = None,
            raise_on_violation: bool = True) -> dict:
        trace.enable()        # flight recorder armed for the whole storm
        t0 = time.monotonic()
        consumers = []
        violation: Optional[OracleViolation] = None
        try:
            for i in range(self.n_consumers):
                delay = (self.consumer_start_delays[i]
                         if i < len(self.consumer_start_delays) else 0.0)
                th = threading.Thread(target=self._consume_loop,
                                      args=(i, delay),
                                      name=f"chaos-consumer-{i}",
                                      daemon=True)
                th.start()
                consumers.append(th)
            # churners: staggered joins, bounded lifetimes — their
            # overlap is the group join/leave storm
            for j in range(self.churn_consumers):
                idx = self.n_consumers + j
                delay = self.churn_start_s + j * self.churn_period_s
                th = threading.Thread(target=self._consume_loop,
                                      args=(idx, delay,
                                            self.churn_lifetime_s),
                                      name=f"chaos-consumer-{idx}",
                                      daemon=True)
                th.start()
                consumers.append(th)

            p = self._make_producer()
            try:
                if self.transactional:
                    p.init_transactions(30)
                self.chaos.start(schedule)
                deadline = time.monotonic() + self.duration_s
                if self.transactional:
                    self._produce_txns(p, deadline)
                else:
                    self._produce_plain(p, deadline)
                self.chaos.join(timeout=schedule.duration + 30)
                self.chaos.heal()
                left = p.flush(60)
                if left:
                    self.errors.append(f"flush left {left} undelivered")
            finally:
                self.chaos.stop()
                p.close()

            # drain: consumers keep polling until every committed ack
            # arrived (or the deadline turns the gap into a loss verdict)
            drain_end = time.monotonic() + self.drain_s
            while (self.oracle.missing_count() > 0
                   and time.monotonic() < drain_end):
                time.sleep(0.2)
            # one extra grace round so trailing duplicates/reorders
            # land in the ledger too, not just the last missing ack
            time.sleep(0.5)

            # group-invariant storms: the still-live members must
            # settle into one exact cover of the partitions; the time
            # that takes (from storm end) is the convergence metric
            group_snapshot = None
            if self.check_group:
                conv_t0 = time.monotonic()
                conv_end = conv_t0 + self.converge_s
                while time.monotonic() < conv_end:
                    if self.oracle.group_coverage(
                            self.topic, self.partitions)["converged"]:
                        self._converged_s = round(
                            time.monotonic() - conv_t0, 2)
                        break
                    time.sleep(0.2)
                # freeze the verdict BEFORE teardown: stopping the
                # consumers is a deliberate LeaveGroup cascade that a
                # live recompute would misread as lost coverage
                group_snapshot = {
                    "coverage": self.oracle.group_coverage(
                        self.topic, self.partitions),
                    "now": time.monotonic()}

            self._stop_consumers.set()
            for th in consumers:
                th.join(15)

            if tamper is not None:
                tamper(self.oracle)
            group_kwargs = {}
            if self.check_group:
                group_kwargs = {"check_group": True,
                                "group_topic": self.topic,
                                "group_partitions": self.partitions,
                                "converged_s": self._converged_s,
                                "converge_bound_s": self.converge_bound_s,
                                "coverage": group_snapshot["coverage"],
                                "now": group_snapshot["now"]}
            if self.check_continuity:
                group_kwargs.update(check_continuity=True,
                                    flow_stall_s=self.flow_stall_s)
            try:
                report = self.oracle.verify(
                    check_duplicates=self.check_duplicates,
                    check_order=self.check_order,
                    raise_on_violation=raise_on_violation,
                    **group_kwargs)
            except OracleViolation as v:
                violation = v
                report = v.report
            report.update({
                "seed": self.seed,
                "external": self.external,
                "produced": self.produced,
                "wall_s": round(time.monotonic() - t0, 2),
                "timeline": self.chaos.timeline,
                "replay_key": self.chaos.replay_key(),
                "schedule_errors": self.chaos.errors,
                "errors": self.errors,
            })
            metrics = self._storm_metrics(self.chaos.timeline)
            if metrics is not None:
                report["storm_metrics"] = metrics
            if self.external:
                report["proc_events"] = list(self.cluster.proc_events)
            if violation is not None:
                raise violation
            return report
        finally:
            self._stop_consumers.set()
            for th in consumers:
                th.join(15)
            self.chaos.stop()
            if self.sockem is not None:
                self.sockem.kill_all()
            self.cluster.stop()
            trace.disable()


# ----------------------------------------------------------- lite storm --
class LiteStorm:  # lint: ok shared-state
    """A storm over :class:`~.members.LiteMemberFleet` — hundreds-to-
    1000 thread-cheap group members instead of full ``Consumer``
    instances, plus one real paced producer.  The scale tier of the
    consumer-group axis: Storm proves the REAL client's cooperative
    protocol; LiteStorm proves the group machinery (mock coordinator,
    assignor, continuity oracle) at member counts no in-process
    Consumer army could reach.

    shared-state pragma: the producer thread and the fleet's workers
    communicate exclusively through the oracle's declared ledgers and
    the fleet's own declared books; the storm thread reads after
    joins."""

    def __init__(self, *, seed: int, brokers: int = 3,
                 partitions: int = 16, topic: str = "coop",
                 external: bool = False, min_alive: int = 1,
                 members: int = 100, churners: int = 0,
                 churn_start_s: float = 2.0, churn_period_s: float = 0.05,
                 churn_lifetime_s: float = 4.0,
                 strategy: str = "cooperative-sticky", threads: int = 8,
                 heartbeat_s: float = 0.4, member_stagger_s: float = 0.0,
                 duration_s: float = 8.0, pace_ms: float = 2.0,
                 drain_s: float = 30.0, converge_s: float = 40.0,
                 converge_bound_s: Optional[float] = None,
                 check_continuity: bool = True,
                 flow_stall_s: float = 2.0,
                 initial_delay_ms: int = 0):
        self.seed = seed
        self.topic = topic
        self.partitions = partitions
        self.external = external
        self.members = members
        self.churners = churners
        self.duration_s = duration_s
        self.pace_ms = pace_ms
        self.drain_s = drain_s
        self.converge_s = converge_s
        self.converge_bound_s = converge_bound_s
        self.check_continuity = check_continuity
        self.flow_stall_s = flow_stall_s
        if external:
            self.cluster = ClusterHandle(brokers=brokers,
                                         topics={topic: partitions})
        else:
            self.cluster = MockCluster(
                num_brokers=brokers, topics={topic: partitions},
                group_initial_rebalance_delay_ms=initial_delay_ms)
        self.oracle = DeliveryOracle(track_flow=check_continuity)
        self.chaos = ChaosScheduler(self.cluster, None,
                                    min_alive=min_alive)
        self.fleet = LiteMemberFleet(
            self.cluster.bootstrap_servers(), group_id=f"lite-g-{seed}",
            topic=topic, partitions=partitions, members=members,
            oracle=self.oracle, seed=seed, strategy=strategy,
            threads=threads, heartbeat_s=heartbeat_s,
            member_stagger_s=member_stagger_s,
            churn_members=churners, churn_start_s=churn_start_s,
            churn_period_s=churn_period_s,
            churn_lifetime_s=churn_lifetime_s)
        self.produced = 0
        self.errors: list[str] = []
        self._converged_s: Optional[float] = None

    def run(self, schedule: Schedule, *,
            tamper: Optional[Callable] = None,
            raise_on_violation: bool = True) -> dict:
        trace.enable()
        t0 = time.monotonic()
        p = Producer({
            "bootstrap.servers": self.cluster.bootstrap_servers(),
            "linger.ms": 2, "enable.idempotence": True,
            "compression.codec": "none",   # lite fetchers parse raw v2
            "message.send.max.retries": 1000, "retry.backoff.ms": 50,
            "message.timeout.ms": 120000, "reconnect.backoff.ms": 50,
            "reconnect.backoff.max.ms": 1000})
        try:
            self.fleet.start()
            self.chaos.start(schedule)
            deadline = time.monotonic() + self.duration_s
            seq = 0
            while time.monotonic() < deadline:
                v = b"s%08d" % seq
                try:
                    p.produce(self.topic, v,
                              partition=seq % self.partitions,
                              on_delivery=self.oracle.dr())
                    seq += 1
                except KafkaException as e:
                    if e.error.code.name == "_QUEUE_FULL":
                        p.poll(0.05)
                        continue
                    raise
                p.poll(0)
                if self.pace_ms:
                    time.sleep(self.pace_ms / 1000.0)
            self.produced = seq
            self.chaos.join(timeout=schedule.duration + 30)
            self.chaos.heal()
            left = p.flush(60)
            if left:
                self.errors.append(f"flush left {left} undelivered")
            storm_end = time.monotonic()

            drain_end = time.monotonic() + self.drain_s
            while (self.oracle.missing_count() > 0
                   and time.monotonic() < drain_end):
                time.sleep(0.2)

            conv_end = storm_end + self.converge_s
            while time.monotonic() < conv_end:
                if self.oracle.group_coverage(
                        self.topic, self.partitions)["converged"]:
                    self._converged_s = round(
                        time.monotonic() - storm_end, 2)
                    break
                time.sleep(0.2)
            group_snapshot = {
                "coverage": self.oracle.group_coverage(self.topic,
                                                       self.partitions),
                "now": time.monotonic()}
            unavail = self.fleet.partition_unavailability(
                group_snapshot["now"])
            self.fleet.stop()

            if tamper is not None:
                tamper(self.oracle)
            violation: Optional[OracleViolation] = None
            try:
                report = self.oracle.verify(
                    check_duplicates=False, check_order=False,
                    check_group=True, group_topic=self.topic,
                    group_partitions=self.partitions,
                    converged_s=self._converged_s,
                    converge_bound_s=self.converge_bound_s,
                    check_continuity=self.check_continuity,
                    flow_stall_s=self.flow_stall_s,
                    coverage=group_snapshot["coverage"],
                    now=group_snapshot["now"],
                    raise_on_violation=raise_on_violation)
            except OracleViolation as v:
                violation = v
                report = v.report
            report.update({
                "seed": self.seed,
                "external": self.external,
                "produced": self.produced,
                "members": self.members + self.churners,
                "live_members": self.fleet.live_member_count(),
                "converged_s": self._converged_s,
                "partition_unavailability": unavail,
                "rebalancing_intervals":
                    len(self.fleet.rebalancing_intervals(
                        group_snapshot["now"])),
                "wall_s": round(time.monotonic() - t0, 2),
                "timeline": self.chaos.timeline,
                "replay_key": self.chaos.replay_key(),
                "schedule_errors": self.chaos.errors,
                "errors": self.errors + list(self.fleet.errors),
            })
            with self.oracle._lock:
                acked_ts = list(self.oracle.acked_ts)
            metrics = storm_metrics(self.chaos.timeline, acked_ts)
            if metrics is not None:
                report["storm_metrics"] = metrics
            if self.external:
                report["proc_events"] = list(self.cluster.proc_events)
            if violation is not None:
                raise violation
            return report
        finally:
            self.fleet.stop()
            self.chaos.stop()
            p.close()
            self.cluster.stop()
            trace.disable()


# ------------------------------------------------------------ scenarios --
def rolling_restart_eos(seed: int = 1, *, kills: int = 5,
                        raise_on_violation: bool = True) -> dict:
    """In-process flagship (ISSUE 7): >=5 rolling broker kill/restarts
    under sustained transactional produce + read_committed consume."""
    interval = 1.2
    storm = Storm(seed=seed, brokers=3, partitions=4, min_alive=2,
                  transactional=True, txn_size=5, abort_every=7,
                  duration_s=1.0 + kills * interval + 0.5, pace_ms=2,
                  drain_s=30.0)
    sched = Schedule(seed=seed)
    for i in range(kills):
        t = 1.0 + i * interval
        sched.at(t, broker_kill("any"))
        sched.at(t + 0.7, broker_restart())    # revive in kill order
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    kills_fired = sum(1 for e in report["timeline"]
                      if e["action"] == "broker_kill"
                      and (e.get("resolved") or {}).get("broker"))
    report["kills_fired"] = kills_fired
    return report


def external_kill9_eos(seed: int = 21, *, kills: int = 3,
                       raise_on_violation: bool = True) -> dict:
    """FLAGSHIP (ISSUE 9): >=3 ``SIGKILL``s of real broker OS
    processes — pid-verified dead — under sustained EOS produce +
    read_committed consume; the oracle asserts all four delivery
    invariants PLUS the group invariants (the consumer must re-acquire
    full coverage after every kill, converge, and never wedge).

    The EOS consumer is a single-member group: zero-duplication across
    partition OWNERSHIP TRANSFER would require transactional offset
    commits (a consume-transform-produce loop), which this storm does
    not run — multi-member assignment churn is covered at-least-once
    by ``group_churn_coordinator_storm``/``fast_group_churn``."""
    interval = 1.8
    storm = Storm(seed=seed, brokers=3, partitions=4, min_alive=2,
                  external=True, transactional=True, txn_size=4,
                  abort_every=6, consumers=1, check_group=True,
                  duration_s=1.0 + kills * interval + 0.5, pace_ms=2,
                  drain_s=40.0)
    sched = Schedule(seed=seed)
    for i in range(kills):
        t = 1.0 + i * interval
        sched.at(t, proc_kill9("any"))
        sched.at(t + 1.0, proc_restart())      # respawn in kill order
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    report["kills_fired"] = sum(
        1 for e in report["timeline"] if e["action"] == "proc_kill9"
        and (e.get("resolved") or {}).get("broker"))
    report["pids_killed"] = [e for e in report.get("proc_events", [])
                             if e["verb"] == "kill9"]
    return report


def coordinator_death_midcommit(seed: int = 2, *, rounds: int = 3,
                                raise_on_violation: bool = True) -> dict:
    """Kill the transaction coordinator while commits are in flight;
    the client must FindCoordinator its way to the failover broker and
    the retried EndTxn must stay idempotent (no torn txns)."""
    storm = Storm(seed=seed, brokers=3, partitions=2, min_alive=2,
                  transactional=True, txn_size=4, abort_every=5,
                  duration_s=1.0 + rounds * 2.0, pace_ms=2, drain_s=30.0)
    tid = f"chaos-tx-{seed}"          # Storm._make_producer's txn id
    sched = Schedule(seed=seed)
    for i in range(rounds):
        t = 1.0 + i * 2.0
        sched.at(t, broker_kill(f"coordinator:{tid}"))
        sched.at(t + 1.0, broker_restart())
    return storm.run(sched, raise_on_violation=raise_on_violation)


def leader_migration_midbatch(seed: int = 3, *, migrations: int = 8,
                              raise_on_violation: bool = True) -> dict:
    """Migrate partition leadership every 400 ms while an idempotent
    producer streams batches: every NOT_LEADER redirect must re-route
    without loss, duplication, or reorder."""
    storm = Storm(seed=seed, brokers=3, partitions=4,
                  duration_s=1.0 + migrations * 0.4, pace_ms=2,
                  drain_s=20.0)
    sched = Schedule(seed=seed).every(
        0.8, 0.4, migrations, lambda: leader_migrate("chaos", "any"))
    return storm.run(sched, raise_on_violation=raise_on_violation)


def slow_network_rebalance(seed: int = 4, *,
                           raise_on_violation: bool = True) -> dict:
    """Slow, jittery, briefly half-partitioned network while a second
    consumer joins mid-stream (eager rebalance): plain consumer-group
    semantics are at-least-once, so only zero-loss is asserted —
    duplicates/reorder across the handoff are legal here."""
    storm = Storm(seed=seed, brokers=2, partitions=4, use_sockem=True,
                  consumers=2, consumer_start_delays=(0.0, 1.5),
                  isolation="read_uncommitted",
                  check_duplicates=False, check_order=False,
                  duration_s=4.5, pace_ms=3, drain_s=25.0)
    sched = (Schedule(seed=seed)
             .at(0.5, net(delay_ms=120, jitter_ms=80))
             .at(2.0, net(rx_drop=True))          # half-open partition
             .at(2.6, net(rx_drop=False))
             .at(3.2, conn_kill())
             .at(4.0, net(delay_ms=0, jitter_ms=0)))
    return storm.run(sched, raise_on_violation=raise_on_violation)


def group_churn_coordinator_storm(seed: int = 31, *, consumers: int = 12,
                                  churners: int = 8,
                                  raise_on_violation: bool = True) -> dict:
    """Consumer-group-heavy storm: a large group (``consumers`` stable
    members + ``churners`` joining/leaving on overlapping lifetimes)
    rebalances continuously while the GROUP coordinator broker is
    killed twice mid-churn.  At-least-once delivery (duplicates across
    handoffs are legal) but zero loss — and the group invariants must
    hold: the survivors converge to one exact cover of the partitions
    and nobody ends up permanently stuck."""
    gid = f"chaos-g-{seed}"
    storm = Storm(seed=seed, brokers=3, partitions=8, min_alive=2,
                  consumers=consumers,
                  consumer_start_delays=tuple(0.05 * i
                                              for i in range(consumers)),
                  check_group=True, converge_s=25.0,
                  churn_consumers=churners, churn_start_s=1.0,
                  churn_period_s=0.45, churn_lifetime_s=2.2,
                  isolation="read_uncommitted",
                  check_duplicates=False, check_order=False,
                  duration_s=6.0, pace_ms=2, drain_s=30.0)
    sched = (Schedule(seed=seed)
             .at(1.6, broker_kill(f"coordinator:{gid}"))
             .at(2.8, broker_restart())
             .at(3.8, broker_kill(f"coordinator:{gid}"))
             .at(5.0, broker_restart()))
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    report["converged_s"] = storm._converged_s
    return report


def fast_kill_restart(seed: int = 7, *,
                      raise_on_violation: bool = True) -> dict:
    """Tier-1 deterministic smoke (<10 s): one broker kill + restart
    under idempotent produce/consume, full invariant check."""
    storm = Storm(seed=seed, brokers=2, partitions=2, min_alive=1,
                  duration_s=2.2, pace_ms=2, drain_s=15.0)
    sched = (Schedule(seed=seed)
             .at(0.7, broker_kill("any"))
             .at(1.5, broker_restart()))
    return storm.run(sched, raise_on_violation=raise_on_violation)


def fast_external_kill9(seed: int = 23, *,
                        raise_on_violation: bool = True) -> dict:
    """Tier-1 out-of-process smoke (<15 s): one real ``SIGKILL`` of a
    broker OS process (pid-verified) + one SIGSTOP/SIGCONT brownout
    under idempotent produce/consume, full invariant check.  Also the
    source of the bench ``storm_msgs_s``/recovery metrics."""
    storm = Storm(seed=seed, brokers=2, partitions=2, min_alive=1,
                  external=True, duration_s=3.0, pace_ms=2, drain_s=20.0)
    sched = (Schedule(seed=seed)
             .at(0.6, proc_pause("any"))
             .at(1.2, proc_cont())
             .at(1.6, proc_kill9("any"))
             .at(2.4, proc_restart()))
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    report["pids_killed"] = [e for e in report.get("proc_events", [])
                             if e["verb"] == "kill9"]
    return report


def fast_session_kill9(seed: int = 57, *,
                       raise_on_violation: bool = True) -> dict:
    """Tier-1 fetch-session chaos smoke (<15 s, ISSUE 14): one real
    ``SIGKILL`` of a broker OS process (pid-verified) under idempotent
    produce + consume with KIP-227 incremental fetch sessions on.  The
    session cache is broker MEMORY — it dies with the process — so the
    reconnecting client must renegotiate from epoch 0 (a fresh full
    fetch) and keep delivering with zero acked loss.  Asserted off the
    per-member ``FetchSession`` counters the storm snapshots at
    teardown.  Broker 1 is SIGKILLed and restarted, then broker 2 is
    SIGKILLed — failing every partition back onto broker 1, so the
    client MUST renegotiate the session its disconnect reset: broker
    1's counters deterministically show resets >= 1 AND full_fetches
    >= 2 (the initial create + the post-kill renegotiation)."""
    storm = Storm(seed=seed, brokers=2, partitions=2, min_alive=1,
                  external=True, duration_s=4.0, pace_ms=2, drain_s=20.0)
    sched = (Schedule(seed=seed)
             .at(1.4, proc_kill9(1))
             .at(2.2, proc_restart())
             .at(2.8, proc_kill9(2))
             .at(3.6, proc_restart()))
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    report["pids_killed"] = [e for e in report.get("proc_events", [])
                             if e["verb"] == "kill9"]
    fs = storm.fetch_session_stats.get("c0", {})
    report["fetch_sessions"] = fs
    if raise_on_violation:
        assert len(report["pids_killed"]) == 2 and all(
            e["verified_dead"] for e in report["pids_killed"]), \
            "expected two pid-verified SIGKILLs"
        b1 = next((s for n, s in fs.items() if n.endswith("/1")), None)
        assert b1 is not None, f"no broker-1 session stats: {list(fs)}"
        assert b1["resets"] >= 1, \
            "broker SIGKILL never reset the fetch session"
        assert b1["full_fetches"] >= 2, \
            "no renegotiation after the broker came back"
        live = [s for s in fs.values() if s["partitions_total"] > 0]
        assert live, "no fetch session was live at teardown"
    return report


def fast_group_churn(seed: int = 33, *,
                     raise_on_violation: bool = True) -> dict:
    """Tier-1 group smoke (<12 s): 4 stable members + 2 churners, one
    coordinator kill mid-rebalance, zero-loss + group invariants."""
    gid = f"chaos-g-{seed}"
    storm = Storm(seed=seed, brokers=2, partitions=4, min_alive=1,
                  consumers=4,
                  consumer_start_delays=(0.0, 0.1, 0.2, 0.3),
                  check_group=True, converge_s=20.0,
                  churn_consumers=2, churn_start_s=0.8,
                  churn_period_s=0.5, churn_lifetime_s=1.2,
                  isolation="read_uncommitted",
                  check_duplicates=False, check_order=False,
                  duration_s=3.0, pace_ms=2, drain_s=20.0)
    sched = (Schedule(seed=seed)
             .at(1.2, broker_kill(f"coordinator:{gid}"))
             .at(2.2, broker_restart()))
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    report["converged_s"] = storm._converged_s
    return report


def fast_cooperative_churn(seed: int = 35, *,
                           raise_on_violation: bool = True) -> dict:
    """Tier-1 cooperative smoke (<14 s): 4 stable + 2 churning REAL
    ``Consumer`` members on the KIP-429 ``cooperative-sticky``
    protocol, one coordinator kill mid-rebalance.  Zero-loss + group
    invariants PLUS the continuity invariant: every partition a member
    keeps through a rebalance must keep flowing (zero stop-the-world
    windows), and convergence lands inside the stated bound."""
    gid = f"chaos-g-{seed}"
    storm = Storm(seed=seed, brokers=2, partitions=4, min_alive=1,
                  consumers=4,
                  consumer_start_delays=(0.0, 0.1, 0.2, 0.3),
                  check_group=True, converge_s=20.0,
                  strategy="cooperative-sticky",
                  check_continuity=True, flow_stall_s=2.5,
                  converge_bound_s=20.0,
                  churn_consumers=2, churn_start_s=0.8,
                  churn_period_s=0.5, churn_lifetime_s=1.2,
                  isolation="read_uncommitted",
                  check_duplicates=False, check_order=False,
                  duration_s=3.5, pace_ms=2, drain_s=20.0)
    sched = (Schedule(seed=seed)
             .at(1.2, broker_kill(f"coordinator:{gid}"))
             .at(2.2, broker_restart()))
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    report["converged_s"] = storm._converged_s
    return report


def cooperative_coordinator_storm(seed: int = 37, *, consumers: int = 12,
                                  churners: int = 8,
                                  raise_on_violation: bool = True) -> dict:
    """Cooperative twin of ``group_churn_coordinator_storm`` (slow):
    12 stable + 8 churning cooperative-sticky members rebalance
    continuously while the group coordinator is killed TWICE
    mid-rebalance — zero loss, group invariants, and the continuity
    invariant across every window."""
    gid = f"chaos-g-{seed}"
    storm = Storm(seed=seed, brokers=3, partitions=8, min_alive=2,
                  consumers=consumers,
                  consumer_start_delays=tuple(0.05 * i
                                              for i in range(consumers)),
                  check_group=True, converge_s=25.0,
                  strategy="cooperative-sticky",
                  check_continuity=True, flow_stall_s=2.5,
                  converge_bound_s=25.0,
                  churn_consumers=churners, churn_start_s=1.0,
                  churn_period_s=0.45, churn_lifetime_s=2.2,
                  isolation="read_uncommitted",
                  check_duplicates=False, check_order=False,
                  duration_s=6.0, pace_ms=2, drain_s=30.0)
    sched = (Schedule(seed=seed)
             .at(1.6, broker_kill(f"coordinator:{gid}"))
             .at(2.8, broker_restart())
             .at(3.8, broker_kill(f"coordinator:{gid}"))
             .at(5.0, broker_restart()))
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    report["converged_s"] = storm._converged_s
    return report


def cooperative_churn_storm(seed: int = 55, *, members: int = 240,
                            churners: int = 80, external: bool = True,
                            kills: int = 1,
                            raise_on_violation: bool = True) -> dict:
    """FLAGSHIP (ISSUE 12): ≥300 thread-cheap cooperative members —
    ``members`` stable + ``churners`` on overlapping join/leave
    lifetimes — against the supervised out-of-process cluster, with
    the group COORDINATOR process SIGKILLed (pid-verified) mid-churn,
    i.e. mid-rebalance: the churn keeps the group permanently
    rebalancing.  The oracle asserts zero acked loss, exact final
    coverage, no stuck member, **zero stop-the-world windows** (every
    kept partition flows through every rebalance window — the
    continuity invariant) and convergence within the stated bound.
    Same seed ⇒ identical ``replay_key`` across supervisor launches
    (the PR 9 contract, now at 1000-member scale)."""
    gid = f"lite-g-{seed}"
    storm = LiteStorm(seed=seed, brokers=3, partitions=16,
                      external=external, min_alive=2,
                      members=members, churners=churners,
                      churn_start_s=2.0, churn_period_s=0.05,
                      churn_lifetime_s=4.0,
                      strategy="cooperative-sticky", threads=8,
                      heartbeat_s=0.5, member_stagger_s=0.004,
                      duration_s=4.0 + churners * 0.05 + 4.0,
                      pace_ms=2, drain_s=40.0,
                      converge_s=45.0, converge_bound_s=45.0,
                      check_continuity=True, flow_stall_s=3.0)
    sched = Schedule(seed=seed)
    kill_verb = proc_kill9 if external else broker_kill
    restart_verb = proc_restart if external else broker_restart
    for i in range(kills):
        t = 4.0 + i * 3.0
        sched.at(t, kill_verb(f"coordinator:{gid}"))
        sched.at(t + 1.5, restart_verb())
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    report["kills_fired"] = sum(
        1 for e in report["timeline"]
        if e["action"] in ("proc_kill9", "broker_kill")
        and (e.get("resolved") or {}).get("broker"))
    if external:
        report["pids_killed"] = [e for e in report.get("proc_events", [])
                                 if e["verb"] == "kill9"]
    return report


def oracle_continuity_selftest(seed: int = 39) -> dict:
    """Intentionally broken continuity: a quiet cooperative run whose
    ledger is tampered with a SYNTHETIC flow gap — a rebalance window
    over an unrevoked partition whose consume stamps inside the window
    are deleted.  Proves the flow-gap detector yields an
    OracleViolation carrying the JSON diff + flight dump (mirrors
    ``oracle_selftest``).  Returns the report (ok=False)."""
    topic = "chaos"

    def _tamper(oracle: DeliveryOracle):
        with oracle._lock:
            stamps = sorted(oracle.flow.get((topic, 0), ()))
            if len(stamps) < 4:
                raise AssertionError(
                    "continuity self-test: no flow recorded to tamper")
            w0, w1 = stamps[0], stamps[-1]
            # plant: a window claiming (topic, 0) was kept throughout,
            # then erase its stamps after the first 10% of the window
            oracle.windows.append(
                ("selftest-m", w0, w1, frozenset({(topic, 0)})))
            cut = w0 + (w1 - w0) * 0.1
            oracle.flow[(topic, 0)] = [t for t in stamps if t <= cut]

    storm = Storm(seed=seed, brokers=1, partitions=2, consumers=1,
                  check_group=True, strategy="cooperative-sticky",
                  check_continuity=True, flow_stall_s=1.0,
                  isolation="read_uncommitted",
                  check_duplicates=False, check_order=False,
                  duration_s=3.0, pace_ms=2, drain_s=12.0)
    try:
        storm.run(Schedule(seed=seed), tamper=_tamper)
    except OracleViolation as v:
        if not v.report["violations"].get("flow_gap"):
            raise AssertionError(
                "continuity self-test: violation raised but no "
                "flow_gap row — wrong detector fired") from v
        return v.report
    raise AssertionError("continuity self-test: planted flow gap was "
                         "not flagged — the continuity oracle is blind")


def fast_net_flap(seed: int = 11, *,
                  raise_on_violation: bool = True) -> dict:
    """Tier-1 deterministic smoke (<10 s): partial writes, latency
    jitter and a mid-flight connection kill via sockem, full invariant
    check on a single-broker cluster."""
    storm = Storm(seed=seed, brokers=1, partitions=2, use_sockem=True,
                  duration_s=2.2, pace_ms=2, drain_s=15.0)
    sched = (Schedule(seed=seed)
             .at(0.3, net(max_write=7))
             .at(0.8, net(delay_ms=80, jitter_ms=40, max_write=0))
             .at(1.3, conn_kill())
             .at(1.7, net(delay_ms=0, jitter_ms=0)))
    return storm.run(sched, raise_on_violation=raise_on_violation)


def soak_kill9_txn_storm(seed: int = 41, *, minutes: float = 2.5,
                         raise_on_violation: bool = True) -> dict:
    """LONG SOAK (``scripts/chaos.sh --soak``): minutes of unpaced EOS
    transactions against the external cluster under repeated
    ``SIGKILL``/respawn cycles — the endurance tier: thousands of
    txns, a kill every ~4 s, every invariant checked at the end."""
    duration = minutes * 60.0
    cycle = 4.0
    cycles = max(1, int((duration - 3.0) / cycle))
    storm = Storm(seed=seed, brokers=3, partitions=4, min_alive=2,
                  external=True, transactional=True, txn_size=3,
                  abort_every=9, consumers=1, check_group=True,
                  duration_s=duration, pace_ms=0, drain_s=60.0)
    sched = Schedule(seed=seed)
    for i in range(cycles):
        t = 2.0 + i * cycle
        sched.at(t, proc_kill9("any"))
        sched.at(t + 2.0, proc_restart())
    report = storm.run(sched, raise_on_violation=raise_on_violation)
    report["kills_fired"] = sum(
        1 for e in report["timeline"] if e["action"] == "proc_kill9"
        and (e.get("resolved") or {}).get("broker"))
    return report


def oracle_selftest(seed: int = 13) -> dict:
    """Intentionally broken: a quiet run whose ledger is tampered
    (one consumed record dropped = loss; one double-recorded = dup)
    to prove a violation yields an OracleViolation carrying a flight-
    recorder dump + oracle diff. Returns the report (ok=False)."""
    def _tamper(oracle: DeliveryOracle):
        with oracle._lock:
            if len(oracle.consumed) >= 2:
                oracle.consumed.pop()                    # lose one
                oracle.consumed.append(oracle.consumed[0])   # dup one
    storm = Storm(seed=seed, brokers=1, partitions=1,
                  duration_s=0.8, pace_ms=2, drain_s=10.0)
    try:
        storm.run(Schedule(seed=seed), tamper=_tamper)
    except OracleViolation as v:
        return v.report
    raise AssertionError("oracle self-test: tampered ledger was not "
                         "flagged — the oracle is blind")


def hot_topic_flood(seed: int = 17, *, flood_s: float = 2.0,
                    raise_on_violation: bool = True) -> dict:
    """QoS isolation under a bulk flood (ISSUE 17): one producer runs a
    latency-sensitive topic (``topic.qos.weight`` 8.0) and a zipf-sized
    bulk topic (weight 0.25) through the device compress route with the
    governor's weighted fan-in + shed model live.  The latency topic's
    produce→ack p99 is measured unloaded, then again with the flood
    active — isolation holds when the flooded p99 stays within 3× the
    unloaded p99 (with an absolute floor: on a 1-core CI host the
    unloaded p99 can be a fraction of a millisecond, where 3× is
    noise), every latency message acks, and the bulk topic still makes
    progress (weighting dims, never starves).  Warmup stays ON — the
    warm gate serving cold buckets from the bit-exact CPU encoder is
    exactly what keeps an XLA compile out of the latency path."""
    import random as _random

    rng = _random.Random(seed)
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "compression.backend": "tpu",
                  "tpu.transport.min.mb.s": 0,
                  "tpu.compress.device": True,
                  "tpu.launch.min.batches": 1,
                  "tpu.governor": True, "tpu.warmup": True,
                  "compression.codec": "lz4", "linger.ms": 2,
                  "batch.num.messages": 32})
    report: dict = {"ok": False, "seed": seed}
    try:
        p._rk.set_topic_conf("qos-latency", {"topic.qos.weight": 8.0})
        p._rk.set_topic_conf("qos-bulk", {"topic.qos.weight": 0.25})

        lat_lock = new_lock("chaos.hot_topic_flood")
        lat_unloaded: list[float] = []
        lat_flood: list[float] = []
        bulk_acked = [0]

        def lat_dr(sink, t0, err, _msg):
            if err is None:
                with lat_lock:
                    sink.append((time.perf_counter() - t0) * 1e3)

        def bulk_dr(err, _msg):
            if err is None:
                with lat_lock:
                    bulk_acked[0] += 1

        def ping(sink):
            t0 = time.perf_counter()
            p.produce("qos-latency", value=b"lat-ping " * 40,
                      on_delivery=lambda e, m, s=sink, t=t0:
                      lat_dr(s, t, e, m))

        # -- phase 1: unloaded baseline ------------------------------
        for _ in range(40):
            ping(lat_unloaded)
            p.poll(0.01)
        p.flush(60)

        # -- phase 2: zipf bulk flood + concurrent latency pings -----
        stop = threading.Event()
        sent_bulk = [0]

        def flood():
            while not stop.is_set():
                # zipf-skewed bulk payloads: mostly small, heavy tail
                n = min(int(2000 * (1.0 / (1.0 - rng.random()) ** 1.2)),
                        120_000)
                try:
                    p.produce("qos-bulk", value=b"\xa5" * max(n, 100),
                              on_delivery=bulk_dr)
                    sent_bulk[0] += 1
                except BufferError:
                    time.sleep(0.002)
                time.sleep(0.0005)

        flooder = threading.Thread(target=flood, name="qos-flooder",
                                   daemon=True)
        flooder.start()
        t_end = time.monotonic() + flood_s
        while time.monotonic() < t_end:
            ping(lat_flood)
            p.poll(0.02)
        stop.set()
        flooder.join(10)
        p.flush(120)

        import json as _json
        stats = _json.loads(p._rk.stats.emit_json())
        comp = stats["codec_engine"]["compress"]
        with lat_lock:
            p99_un = _pct(lat_unloaded, 0.99)
            p99_fl = _pct(lat_flood, 0.99)
            n_un, n_fl = len(lat_unloaded), len(lat_flood)
            bulk_n = bulk_acked[0]
        # 3× isolation bound with an absolute floor (sub-ms unloaded
        # p99s make a pure ratio meaningless on shared CI hosts)
        bound = max(3.0 * (p99_un or 0.0), 100.0)
        pings = 40 + n_fl
        report.update({
            "p99_unloaded_ms": p99_un, "p99_flood_ms": p99_fl,
            "bound_ms": round(bound, 1), "latency_acked": n_un + n_fl,
            "latency_sent": pings, "bulk_sent": sent_bulk[0],
            "bulk_acked": bulk_n, "compress": comp,
            "qos": comp["qos"]})
        ok = (p99_fl is not None and p99_fl <= bound
              and n_un + n_fl == pings        # every latency msg acked
              and bulk_n > 0)                # flood progressed too
        report["ok"] = ok
        if raise_on_violation and not ok:
            raise AssertionError(
                f"QoS isolation violated: flood p99 {p99_fl}ms vs "
                f"bound {bound:.1f}ms (unloaded {p99_un}ms), "
                f"latency acked {n_un + n_fl}/{pings}, "
                f"bulk acked {bulk_n}")
        return report
    finally:
        p.close()


class Scenario(NamedTuple):
    fn: Callable
    desc: str
    tier: str          # "fast" (tier-1) | "slow" | "soak"
    seed: int          # default seed (CLI --seed overrides = replay)
    invariants: str    # what the oracle asserts for this storm


SCENARIOS: dict[str, Scenario] = {
    "rolling_restart_eos": Scenario(
        rolling_restart_eos,
        "in-process flagship: >=5 rolling broker kill/restarts under "
        "EOS produce + read_committed consume", "slow", 1,
        "loss,dup,order,atomicity"),
    "external_kill9_eos": Scenario(
        external_kill9_eos,
        "OUT-OF-PROCESS flagship: >=3 SIGKILLs of real broker OS "
        "processes (pid-verified) under EOS + read_committed, "
        "2-member group", "slow", 21,
        "loss,dup,order,atomicity,group"),
    "coordinator_death_midcommit": Scenario(
        coordinator_death_midcommit,
        "kill the txn coordinator mid-commit; EndTxn retry must stay "
        "idempotent across failover", "slow", 2,
        "loss,dup,order,atomicity"),
    "leader_migration_midbatch": Scenario(
        leader_migration_midbatch,
        "migrate partition leaders every 400ms under idempotent "
        "produce", "slow", 3, "loss,dup,order"),
    "slow_network_rebalance": Scenario(
        slow_network_rebalance,
        "slow/jittery/half-partitioned network during a consumer-group "
        "rebalance (zero-loss)", "slow", 4, "loss"),
    "group_churn_coordinator_storm": Scenario(
        group_churn_coordinator_storm,
        "12 stable + 8 churning consumers rebalance while the group "
        "coordinator dies twice", "slow", 31, "loss,group"),
    "fast_kill_restart": Scenario(
        fast_kill_restart,
        "tier-1 smoke: one kill/restart, full invariants, <10s",
        "fast", 7, "loss,dup,order"),
    "fast_external_kill9": Scenario(
        fast_external_kill9,
        "tier-1 smoke: real SIGKILL + SIGSTOP brownout of broker OS "
        "processes, <15s", "fast", 23, "loss,dup,order"),
    "fast_session_kill9": Scenario(
        fast_session_kill9,
        "tier-1 smoke: pid-verified broker SIGKILL under incremental "
        "fetch sessions — session dies with the broker, client "
        "renegotiates, zero loss, <15s", "fast", 57, "loss,dup,order"),
    "fast_group_churn": Scenario(
        fast_group_churn,
        "tier-1 smoke: 4+2-member group churn across a coordinator "
        "kill, <12s", "fast", 33, "loss,group"),
    "fast_cooperative_churn": Scenario(
        fast_cooperative_churn,
        "tier-1 smoke: 4+2 cooperative-sticky members churn across a "
        "coordinator kill — continuity invariant on, <14s",
        "fast", 35, "loss,group,continuity"),
    "cooperative_coordinator_storm": Scenario(
        cooperative_coordinator_storm,
        "12+8 cooperative-sticky members rebalance while the "
        "coordinator dies twice — zero stop-the-world windows",
        "slow", 37, "loss,group,continuity"),
    "cooperative_churn_storm": Scenario(
        cooperative_churn_storm,
        "FLAGSHIP: >=300 thread-cheap cooperative members under "
        "overlapping join/leave churn + a pid-verified coordinator "
        "SIGKILL mid-rebalance — continuity + bounded convergence",
        "slow", 55, "loss,group,continuity,convergence-bound"),
    "oracle_continuity_selftest": Scenario(
        oracle_continuity_selftest,
        "intentionally broken: a synthetic flow gap on an unrevoked "
        "partition must dump flight + diff", "fast", 39, "selftest"),
    "fast_net_flap": Scenario(
        fast_net_flap,
        "tier-1 smoke: partial writes + jitter + conn kill, <10s",
        "fast", 11, "loss,dup,order"),
    "soak_kill9_txn_storm": Scenario(
        soak_kill9_txn_storm,
        "SOAK: minutes of unpaced EOS txns under repeated SIGKILL "
        "cycles of real broker processes", "soak", 41,
        "loss,dup,order,atomicity,group"),
    "oracle_selftest": Scenario(
        oracle_selftest,
        "intentionally broken ledger proves violations dump flight + "
        "diff", "fast", 13, "selftest"),
    "hot_topic_flood": Scenario(
        hot_topic_flood,
        "tier-1 smoke: zipf bulk flood vs a weight-8 latency topic on "
        "the device compress route — flooded p99 within 3x unloaded, "
        "<10s", "fast", 17, "qos-isolation"),
}
