/* tkafka.hpp — header-only C++ RAII wrapper over libtkafka.so.
 *
 * The rebuild's src-cpp/ analog (reference: src-cpp/rdkafkacpp.h — a
 * thin delegating wrapper over the C ABI with callbacks trampolined
 * through C function pointers). Class surface mirrors the RdKafka::
 * namespace shape in miniature: Conf, Producer, Consumer, Message,
 * DeliveryReportCb, EventCb.
 *
 * Ownership rules match the reference wrapper:
 *   - Producer/Consumer: heap-allocated via create(), delete closes.
 *   - Message: returned by Consumer::consume(); caller deletes (frees
 *     the underlying tk_msg_t).
 *   - Conf: plain value type; set() before create().
 */
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <utility>
#include <vector>

#include "tkafka.h"

namespace tkafka {

enum ErrorCode { ERR_NO_ERROR = 0, ERR_UNKNOWN = -1 };

inline std::string version() {
    char buf[64];
    return tk_version(buf, sizeof buf) > 0 ? std::string(buf)
                                           : std::string();
}

inline std::string err2str(int err) {
    char buf[128];
    return tk_err2str(err, buf, sizeof buf) > 0 ? std::string(buf)
                                                : std::string("UNKNOWN");
}

/* ------------------------------------------------------------- Conf -- */
class Conf {
  public:
    void set(const std::string &name, const std::string &value) {
        kv_[name] = value;
    }
    std::string get(const std::string &name) const {
        auto it = kv_.find(name);
        return it == kv_.end() ? std::string() : it->second;
    }
    /* JSON object for tk_producer_new/tk_consumer_new. Every value is
     * emitted as a quoted string — the conf layer coerces strings to
     * the declared property type exactly like the reference's all-
     * string rd_kafka_conf_set, so "10"/"true"/"007" all arrive with
     * their intended semantics (an unquoted-literal heuristic would
     * retype string-valued properties that merely look numeric). */
    std::string dump_json() const {
        std::string out = "{";
        bool first = true;
        for (const auto &kv : kv_) {
            if (!first) out += ", ";
            first = false;
            out += '"';
            out += escape(kv.first);
            out += "\": \"";
            out += escape(kv.second);
            out += '"';
        }
        return out + "}";
    }

  private:
    static std::string escape(const std::string &s) {
        std::string o;
        char u[8];
        for (unsigned char c : s) {
            if (c == '"' || c == '\\') {
                o += '\\';
                o += static_cast<char>(c);
            } else if (c < 0x20) {
                /* control chars (PEM blobs carry newlines) must be
                 * \u-escaped or json.loads rejects the conf */
                std::snprintf(u, sizeof u, "\\u%04x", c);
                o += u;
            } else {
                o += static_cast<char>(c);
            }
        }
        return o;
    }
    std::map<std::string, std::string> kv_;
};

/* ---------------------------------------------------------- Message -- */
class Message {
  public:
    Message() { std::memset(&m_, 0, sizeof m_); }
    explicit Message(const tk_msg_t &m) : own_(true), m_(m) {}
    ~Message() {
        if (own_) tk_msg_free(&m_);
    }
    Message(const Message &) = delete;
    Message &operator=(const Message &) = delete;

    int err() const { return m_.err; }
    std::string errstr() const { return err2str(m_.err); }
    std::string topic_name() const {
        return m_.topic ? std::string(m_.topic) : std::string();
    }
    int32_t partition() const { return m_.partition; }
    int64_t offset() const { return m_.offset; }
    int64_t timestamp() const { return m_.timestamp; }
    const void *payload() const { return m_.payload; }
    size_t len() const { return m_.len; }
    const void *key_pointer() const { return m_.key; }
    size_t key_len() const { return m_.key_len; }
    std::string key() const {
        return m_.key ? std::string(m_.key, m_.key_len) : std::string();
    }
    std::string value() const {
        return m_.payload ? std::string(m_.payload, m_.len)
                          : std::string();
    }
    /* Raw-byte header list; a null header value becomes an empty
     * string here — use headers_raw() when the null/empty distinction
     * matters. */
    std::vector<std::pair<std::string, std::string>> headers() const {
        std::vector<std::pair<std::string, std::string>> out;
        for (int i = 0; i < m_.hdr_cnt; i++) {
            out.emplace_back(
                std::string(m_.hdr_names[i]),
                m_.hdr_vals[i]
                    ? std::string(m_.hdr_vals[i], m_.hdr_val_lens[i])
                    : std::string());
        }
        return out;
    }
    /* Headers with the null-value signal preserved (value ignored,
     * null_value=true for headers produced with a NULL value). */
    std::vector<struct Header> headers_raw() const;

  private:
    bool own_ = false;
    tk_msg_t m_;
};

/* ------------------------------------------------- callback classes -- */
class DeliveryReportCb {
  public:
    virtual ~DeliveryReportCb() = default;
    virtual void dr_cb(long long opaque, int err, int32_t partition,
                       int64_t offset) = 0;
};

class EventCb {     /* log + error + stats events (reference EventCb) */
  public:
    virtual ~EventCb() = default;
    virtual void log_cb(int level, const char *fac, const char *msg) {}
    virtual void error_cb(int err, const char *reason) {}
    virtual void stats_cb(const char *json) {}
};

namespace detail {
/* C callbacks can't capture state and the tk_* callback signatures
 * carry no handle — but DR/log/stats callbacks only ever fire inside
 * THIS thread's tk_poll/tk_flush call, so a thread-local "current
 * handle owner" resolves the dispatch (the reference trampolines via
 * rd_kafka_conf_set_opaque instead; the C layer here keeps opaque for
 * per-message use). */
struct Current {
    DeliveryReportCb *dr = nullptr;
    EventCb *ev = nullptr;
};
inline Current &current() {
    thread_local Current c;
    return c;
}
inline void dr_thunk(long long opaque, int err, int32_t partition,
                     int64_t offset) {
    if (current().dr) current().dr->dr_cb(opaque, err, partition, offset);
}
inline void log_thunk(int level, const char *fac, const char *msg) {
    if (current().ev) current().ev->log_cb(level, fac, msg);
}
inline void err_thunk(int err, const char *reason) {
    if (current().ev) current().ev->error_cb(err, reason);
}
inline void stats_thunk(const char *json) {
    if (current().ev) current().ev->stats_cb(json);
}
/* RAII scope: installs this handle's callbacks as the thread's
 * current dispatch targets for the duration of a poll/flush. */
struct Scope {
    Scope(DeliveryReportCb *dr, EventCb *ev) : prev_(current()) {
        current().dr = dr;
        current().ev = ev;
    }
    ~Scope() { current() = prev_; }
    Current prev_;
};
}  // namespace detail

/* ----------------------------------------------------------- Handle -- */
class Handle {
  public:
    virtual ~Handle() {
        if (h_) tk_destroy(h_);
    }
    Handle(const Handle &) = delete;
    Handle &operator=(const Handle &) = delete;

    int poll(int timeout_ms) {
        detail::Scope s(dr_, ev_);
        return tk_poll(h_, timeout_ms);
    }
    long long outq_len() const { return tk_outq_len(h_); }
    bool conf_set(const std::string &n, const std::string &v) {
        return tk_conf_set(h_, n.c_str(), v.c_str()) == 0;
    }
    std::string conf_get(const std::string &n) const {
        char buf[512];
        return tk_conf_get(h_, n.c_str(), buf, sizeof buf) > 0
                   ? std::string(buf)
                   : std::string();
    }
    void set_event_cb(EventCb *ev) {
        ev_ = ev;
        tk_set_log_cb(h_, detail::log_thunk);
        tk_set_error_cb(h_, detail::err_thunk);
        tk_set_stats_cb(h_, detail::stats_thunk);
    }
    std::string mock_bootstrap() const {
        char buf[256];
        return tk_mock_bootstrap(h_, buf, sizeof buf) > 0
                   ? std::string(buf)
                   : std::string();
    }
    tk_handle_t c_handle() const { return h_; }

  protected:
    Handle() = default;
    tk_handle_t h_ = 0;
    DeliveryReportCb *dr_ = nullptr;
    EventCb *ev_ = nullptr;
};

/* --------------------------------------------------------- Producer -- */
struct Header {
    std::string name;
    std::string value;
    bool null_value = false;
};

inline std::vector<Header> Message::headers_raw() const {
    std::vector<Header> out;
    for (int i = 0; i < m_.hdr_cnt; i++) {
        Header h;
        h.name = m_.hdr_names[i];
        if (m_.hdr_vals[i])
            h.value.assign(m_.hdr_vals[i], m_.hdr_val_lens[i]);
        else
            h.null_value = true;
        out.push_back(std::move(h));
    }
    return out;
}

class Producer : public Handle {
  public:
    static Producer *create(const Conf &conf, std::string &errstr) {
        char err[512] = {0};
        tk_handle_t h = tk_producer_new(conf.dump_json().c_str(), err,
                                        sizeof err);
        if (!h) {
            errstr = err;
            return nullptr;
        }
        auto *p = new Producer();
        p->h_ = h;
        return p;
    }
    void set_dr_cb(DeliveryReportCb *cb) {
        dr_ = cb;
        tk_set_dr_cb(h_, detail::dr_thunk);
    }
    int produce(const std::string &topic, int32_t partition,
                const void *payload, size_t len, const void *key = nullptr,
                size_t key_len = 0,
                const std::vector<Header> &headers = {},
                int64_t timestamp_ms = 0, long long opaque = 0) {
        if (headers.empty() && timestamp_ms == 0 && opaque == 0)
            return tk_produce(h_, topic.c_str(), partition,
                              static_cast<const char *>(key), key_len,
                              static_cast<const char *>(payload), len);
        std::vector<const char *> hn, hv;
        std::vector<size_t> hl;
        for (const auto &h : headers) {
            hn.push_back(h.name.c_str());
            hv.push_back(h.null_value ? nullptr : h.value.data());
            hl.push_back(h.null_value ? 0 : h.value.size());
        }
        return tk_produce2(h_, topic.c_str(), partition,
                           static_cast<const char *>(key), key_len,
                           static_cast<const char *>(payload), len,
                           timestamp_ms, hn.data(), hv.data(), hl.data(),
                           static_cast<int>(hn.size()), opaque);
    }
    int flush(int timeout_ms) {
        detail::Scope s(dr_, ev_);
        return tk_flush(h_, timeout_ms);
    }
    int purge(bool in_queue = true, bool in_flight = false) {
        return tk_purge(h_, in_queue, in_flight);
    }
    /* admin conveniences (reference exposes these via AdminClient) */
    int create_topic(const std::string &t, int partitions,
                     int timeout_ms = 10000) {
        return tk_create_topic(h_, t.c_str(), partitions, timeout_ms);
    }
    int delete_topic(const std::string &t, int timeout_ms = 10000) {
        return tk_delete_topic(h_, t.c_str(), timeout_ms);
    }
    int create_partitions(const std::string &t, int new_total,
                          int timeout_ms = 10000) {
        return tk_create_partitions(h_, t.c_str(), new_total,
                                    timeout_ms);
    }
    /* JSON blob results; empty string = error */
    std::string describe_configs(int restype, const std::string &name,
                                 int timeout_ms = 10000) {
        std::string buf(16384, '\0');
        int r = tk_describe_configs(h_, restype, name.c_str(), &buf[0],
                                    (int)buf.size(), timeout_ms);
        if (r <= 0) return std::string();
        buf.resize((size_t)r);
        return buf;
    }
    int alter_configs(int restype, const std::string &name,
                      const std::string &conf_json,
                      int timeout_ms = 10000) {
        return tk_alter_configs(h_, restype, name.c_str(),
                                conf_json.c_str(), timeout_ms);
    }
    std::string list_groups(int timeout_ms = 10000) {
        std::string buf(16384, '\0');
        int r = tk_list_groups(h_, &buf[0], (int)buf.size(), timeout_ms);
        if (r <= 0) return std::string();
        buf.resize((size_t)r);
        return buf;
    }
    std::string describe_group(const std::string &group,
                               int timeout_ms = 10000) {
        std::string buf(16384, '\0');
        int r = tk_describe_group(h_, group.c_str(), &buf[0],
                                  (int)buf.size(), timeout_ms);
        if (r <= 0) return std::string();
        buf.resize((size_t)r);
        return buf;
    }
    int delete_group(const std::string &group, int timeout_ms = 10000) {
        return tk_delete_group(h_, group.c_str(), timeout_ms);
    }

  private:
    Producer() = default;
};

/* --------------------------------------------------------- Consumer -- */
class TopicPartition {
  public:
    TopicPartition(std::string t, int32_t p, int64_t off = -1001)
        : topic(std::move(t)), partition(p), offset(off) {}
    std::string topic;
    int32_t partition;
    int64_t offset;
};

class Consumer : public Handle {
  public:
    static Consumer *create(const Conf &conf, std::string &errstr) {
        char err[512] = {0};
        tk_handle_t h = tk_consumer_new(conf.dump_json().c_str(), err,
                                        sizeof err);
        if (!h) {
            errstr = err;
            return nullptr;
        }
        auto *c = new Consumer();
        c->h_ = h;
        return c;
    }
    int subscribe(const std::vector<std::string> &topics) {
        std::string csv;
        for (const auto &t : topics) {
            if (!csv.empty()) csv += ',';
            csv += t;
        }
        return tk_subscribe(h_, csv.c_str());
    }
    int assign(const std::vector<TopicPartition> &parts) {
        if (parts.empty()) return tk_unassign(h_);
        /* the C surface assigns per topic */
        int rc = 0;
        std::map<std::string,
                 std::pair<std::vector<int32_t>, std::vector<int64_t>>>
            by_topic;
        for (const auto &tp : parts) {
            by_topic[tp.topic].first.push_back(tp.partition);
            by_topic[tp.topic].second.push_back(tp.offset);
        }
        for (const auto &kv : by_topic)
            rc |= tk_assign(h_, kv.first.c_str(), kv.second.first.data(),
                            kv.second.second.data(),
                            static_cast<int>(kv.second.first.size()));
        return rc;
    }
    int unassign() { return tk_unassign(h_); }
    /* nullptr = nothing within the timeout; caller owns the Message */
    Message *consume(int timeout_ms) {
        detail::Scope s(dr_, ev_);
        tk_msg_t m;
        int r = tk_consumer_poll(h_, timeout_ms, &m);
        if (r <= 0) return nullptr;
        return new Message(m);
    }
    int commit(bool async_commit = false) {
        return tk_commit(h_, async_commit);
    }
    long long committed(const std::string &topic, int32_t partition,
                        int timeout_ms = 5000) {
        return tk_committed(h_, topic.c_str(), partition, timeout_ms);
    }
    int seek(const TopicPartition &tp) {
        return tk_seek(h_, tp.topic.c_str(), tp.partition, tp.offset);
    }
    long long position(const std::string &topic, int32_t partition) {
        return tk_position(h_, topic.c_str(), partition);
    }
    int pause(const std::string &topic, int32_t partition) {
        return tk_pause(h_, topic.c_str(), partition);
    }
    int resume(const std::string &topic, int32_t partition) {
        return tk_resume(h_, topic.c_str(), partition);
    }
    int query_watermark_offsets(const std::string &topic,
                                int32_t partition, int64_t *lo,
                                int64_t *hi, int timeout_ms = 5000) {
        return tk_query_watermark_offsets(h_, topic.c_str(), partition,
                                          lo, hi, timeout_ms);
    }

  private:
    Consumer() = default;
};

}  // namespace tkafka
