"""librdkafka_tpu.capi — the C-callable binding surface.

The reference ships a second-language binding (src-cpp/rdkafkacpp.h, a
C++ wrapper over the C ABI). This package is the rebuild's equivalent
in the opposite direction: a real C ABI (libtkafka.so + tkafka.h,
built via cffi's embedding API) exporting producer/consumer entry
points that drive the framework inside an embedded CPython — so C/C++
applications can link against the TPU-native client the same way they
link librdkafka today.

Build:  python -m librdkafka_tpu.capi.build_capi  (writes libtkafka.so
        + tkafka.h next to this file; tests/test_0115_capi.py compiles
        and runs a real C program against it)
"""
