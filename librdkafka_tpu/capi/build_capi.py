"""Build libtkafka.so — a C ABI over the framework via cffi embedding.

API shape follows the reference's C surface in miniature
(/root/reference/src/rdkafka.h: rd_kafka_new/producev/flush/
consumer_poll/...), flattened to the handful of entry points a C app
needs for produce/consume round trips. Configuration crosses the
boundary as a JSON object string — the C caller never sees Python.
"""
from __future__ import annotations

import os

HERE = os.path.dirname(os.path.abspath(__file__))
SO = os.path.join(HERE, "libtkafka.so")
HEADER = os.path.join(HERE, "tkafka.h")

TYPES = r"""
typedef struct tk_msg {
    char   *topic;      /* owned by the message; freed by tk_msg_free */
    int32_t partition;
    int64_t offset;
    int64_t timestamp;  /* ms since epoch, -1 if unset */
    char   *key;        /* NULL when the record has no key */
    size_t  key_len;
    char   *payload;    /* NULL only for null-value records */
    size_t  len;
    int     err;        /* 0 = ok */
} tk_msg_t;

/* Handles are opaque integers (0 = error; details in errstr). */
typedef long long tk_handle_t;
"""

FUNCS = r"""
extern tk_handle_t tk_producer_new(const char *conf_json,
                                   char *errstr, int errstr_size);
extern tk_handle_t tk_consumer_new(const char *conf_json,
                                   char *errstr, int errstr_size);
extern int  tk_produce(tk_handle_t h, const char *topic, int32_t partition,
                       const char *key, size_t key_len,
                       const char *payload, size_t len);
extern int  tk_flush(tk_handle_t h, int timeout_ms);
extern int  tk_subscribe(tk_handle_t h, const char *topics_csv);
extern int  tk_consumer_poll(tk_handle_t h, int timeout_ms, tk_msg_t *out);
extern void tk_msg_free(tk_msg_t *m);
extern int  tk_mock_bootstrap(tk_handle_t h, char *buf, int size);
extern void tk_destroy(tk_handle_t h);
"""

CDEF = TYPES + FUNCS

INIT = r"""
import json
import threading

from librdkafka_tpu import Producer, Consumer
from tkafka_cffi import ffi  # noqa: F401  (the cffi embedding module)

_handles = {}
_next = [1]
_lock = threading.Lock()


def _register(obj):
    with _lock:
        h = _next[0]
        _next[0] += 1
        _handles[h] = obj
    return h


def _fail(errstr, errstr_size, exc):
    msg = str(exc).encode()[: max(0, errstr_size - 1)]
    if errstr != ffi.NULL and errstr_size > 0:
        buf = ffi.buffer(errstr, errstr_size)
        buf[: len(msg)] = msg
        buf[len(msg)] = b"\0"
    return 0


@ffi.def_extern()
def tk_producer_new(conf_json, errstr, errstr_size):
    try:
        conf = json.loads(ffi.string(conf_json).decode())
        return _register(Producer(conf))
    except Exception as e:
        return _fail(errstr, errstr_size, e)


@ffi.def_extern()
def tk_consumer_new(conf_json, errstr, errstr_size):
    try:
        conf = json.loads(ffi.string(conf_json).decode())
        return _register(Consumer(conf))
    except Exception as e:
        return _fail(errstr, errstr_size, e)


@ffi.def_extern()
def tk_produce(h, topic, partition, key, key_len, payload, length):
    p = _handles.get(h)
    if p is None:
        return -1
    try:
        p.produce(ffi.string(topic).decode(),
                  value=bytes(ffi.buffer(payload, length))
                  if payload != ffi.NULL else None,
                  key=bytes(ffi.buffer(key, key_len))
                  if key != ffi.NULL else None,
                  partition=partition)
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_flush(h, timeout_ms):
    p = _handles.get(h)
    if p is None:
        return -1
    try:
        return int(p.flush(timeout_ms / 1000.0))
    except Exception:
        return -1


@ffi.def_extern()
def tk_subscribe(h, topics_csv):
    c = _handles.get(h)
    if c is None:
        return -1
    try:
        c.subscribe([t.strip() for t
                     in ffi.string(topics_csv).decode().split(",")
                     if t.strip()])
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_consumer_poll(h, timeout_ms, out):
    # 1 = message filled into out, 0 = nothing, <0 = error.
    # The caller's stack struct starts as garbage: initialize EVERY
    # field before any early return.
    out.err = 0
    out.topic = ffi.NULL
    out.key = ffi.NULL
    out.payload = ffi.NULL
    out.key_len = 0
    out.len = 0
    out.partition = -1
    out.offset = -1
    out.timestamp = -1
    c = _handles.get(h)
    if c is None:
        return -1
    try:
        m = c.poll(timeout_ms / 1000.0)
    except Exception:
        return -2        # cffi's default-0 would read as "no message"
    if m is None:
        return 0
    if m.error is not None:
        out.err = int(m.error.code)
        return 1
    t = (m.topic or "").encode()
    out.topic = lib_strdup(t)
    out.partition = m.partition
    out.offset = m.offset
    out.timestamp = m.timestamp if m.timestamp else -1
    if m.key is None:
        out.key = ffi.NULL
        out.key_len = 0
    else:
        out.key = lib_memdup(m.key)
        out.key_len = len(m.key)
    if m.value is None:
        out.payload = ffi.NULL
        out.len = 0
    else:
        out.payload = lib_memdup(m.value)
        out.len = len(m.value)
    return 1


_allocs = {}


def lib_memdup(b):
    buf = ffi.new("char[]", bytes(b))
    _allocs[int(ffi.cast("intptr_t", buf))] = buf
    return buf


def lib_strdup(b):
    buf = ffi.new("char[]", bytes(b) + b"\0")
    _allocs[int(ffi.cast("intptr_t", buf))] = buf
    return buf


def _release(ptr):
    if ptr != ffi.NULL:
        _allocs.pop(int(ffi.cast("intptr_t", ptr)), None)


@ffi.def_extern()
def tk_msg_free(m):
    _release(m.topic)
    _release(m.key)
    _release(m.payload)
    m.topic = m.key = m.payload = ffi.NULL


@ffi.def_extern()
def tk_mock_bootstrap(h, buf, size):
    # bootstrap.servers of the handle's in-process mock cluster
    # (test.mock.num.brokers), for wiring a second client to it
    obj = _handles.get(h)
    if obj is None:
        return -1
    cluster = getattr(obj._rk, "mock_cluster", None)
    if cluster is None:
        return -1
    bs = cluster.bootstrap_servers().encode()
    if len(bs) + 1 > size:
        return -1
    b = ffi.buffer(buf, size)
    b[: len(bs)] = bs
    b[len(bs)] = b"\0"
    return len(bs)


@ffi.def_extern()
def tk_destroy(h):
    obj = _handles.pop(h, None)
    if obj is not None:
        try:
            obj.close()
        except Exception:
            pass
"""

HEADER_TEXT = (
    "/* tkafka.h — C API for the librdkafka_tpu framework\n"
    " * (the rebuild's src-cpp/ equivalent: a second-language binding\n"
    " * over the same core; reference surface: src/rdkafka.h).\n"
    " * Link: -ltkafka  (plus the embedded CPython the .so carries). */\n"
    "#pragma once\n"
    "#include <stdint.h>\n"
    "#include <stddef.h>\n"
    "#ifdef __cplusplus\nextern \"C\" {\n#endif\n"
    + CDEF +
    "#ifdef __cplusplus\n}\n#endif\n")


def build(force: bool = False) -> str:
    if not force and os.path.exists(SO) and os.path.exists(HEADER) \
            and os.path.getmtime(SO) >= os.path.getmtime(__file__) \
            and os.path.getmtime(HEADER) >= os.path.getmtime(__file__):
        return SO
    import cffi
    ffibuilder = cffi.FFI()
    ffibuilder.embedding_api(CDEF)
    # the cdef'd types must exist in the generated C too
    ffibuilder.set_source("tkafka_cffi", TYPES)
    ffibuilder.embedding_init_code(INIT)
    ffibuilder.compile(tmpdir=HERE, target=SO, verbose=False)
    with open(HEADER, "w") as f:
        f.write(HEADER_TEXT)
    return SO


if __name__ == "__main__":
    print(build(force=True))
