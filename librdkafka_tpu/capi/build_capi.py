"""Build libtkafka.so — a C ABI over the framework via cffi embedding.

API shape follows the reference's C surface in miniature
(/root/reference/src/rdkafka.h: rd_kafka_new/producev/flush/
consumer_poll/...), flattened to the handful of entry points a C app
needs for produce/consume round trips. Configuration crosses the
boundary as a JSON object string — the C caller never sees Python.
"""
from __future__ import annotations

import os

HERE = os.path.dirname(os.path.abspath(__file__))
SO = os.path.join(HERE, "libtkafka.so")
HEADER = os.path.join(HERE, "tkafka.h")

TYPES = r"""
typedef struct tk_msg {
    char   *topic;      /* owned by the message; freed by tk_msg_free */
    int32_t partition;
    int64_t offset;
    int64_t timestamp;  /* ms since epoch, -1 if unset */
    char   *key;        /* NULL when the record has no key */
    size_t  key_len;
    char   *payload;    /* NULL only for null-value records */
    size_t  len;
    int     err;        /* 0 = ok */
    /* First-class headers (reference rd_kafka_header_get_all): raw
     * byte values, no escaping. All arrays are owned by the message
     * and freed by tk_msg_free. */
    int     hdr_cnt;
    char  **hdr_names;    /* NUL-terminated utf-8 names */
    char  **hdr_vals;     /* raw bytes (NUL-padded); NULL = null value */
    size_t *hdr_val_lens;
} tk_msg_t;

/* Handles are opaque integers (0 = error; details in errstr). */
typedef long long tk_handle_t;

/* Per-message delivery report trampoline (reference dr_msg_cb):
 * err 0 = delivered; opaque is the value passed to tk_produce2. */
typedef void (*tk_dr_cb_t)(long long opaque, int err,
                           int32_t partition, int64_t offset);

/* Observability callbacks (reference rd_kafka_conf_set_log_cb /
 * _set_error_cb / _set_stats_cb). Strings are valid only for the
 * duration of the call — copy if you keep them. They fire on the
 * thread that calls tk_poll/tk_flush (log may also fire on internal
 * threads, like the reference's non-queued log_cb). */
typedef void (*tk_log_cb_t)(int level, const char *fac, const char *msg);
typedef void (*tk_error_cb_t)(int err, const char *reason);
typedef void (*tk_stats_cb_t)(const char *json_str);
"""

FUNCS = r"""
extern tk_handle_t tk_producer_new(const char *conf_json,
                                   char *errstr, int errstr_size);
extern tk_handle_t tk_consumer_new(const char *conf_json,
                                   char *errstr, int errstr_size);
extern int  tk_produce(tk_handle_t h, const char *topic, int32_t partition,
                       const char *key, size_t key_len,
                       const char *payload, size_t len);
extern int  tk_produce2(tk_handle_t h, const char *topic,
                        int32_t partition,
                        const char *key, size_t key_len,
                        const char *payload, size_t len,
                        int64_t timestamp_ms,
                        const char **hdr_names, const char **hdr_vals,
                        const size_t *hdr_val_lens, int hdr_cnt,
                        long long opaque);
extern long long tk_produce_batch(tk_handle_t h, const char *topic,
                                  int32_t partition, const char *base,
                                  const int32_t *klens,
                                  const int32_t *vlens, int count);
extern int  tk_set_dr_cb(tk_handle_t h, tk_dr_cb_t cb);
extern int  tk_poll(tk_handle_t h, int timeout_ms);
extern long long tk_outq_len(tk_handle_t h);
extern int  tk_flush(tk_handle_t h, int timeout_ms);
extern int  tk_subscribe(tk_handle_t h, const char *topics_csv);
extern int  tk_assign(tk_handle_t h, const char *topic,
                      const int32_t *partitions,
                      const int64_t *offsets, int nparts);
extern int  tk_unassign(tk_handle_t h);
extern int  tk_consumer_poll(tk_handle_t h, int timeout_ms, tk_msg_t *out);
extern int  tk_commit(tk_handle_t h, int async_flag);
extern long long tk_committed(tk_handle_t h, const char *topic,
                              int32_t partition, int timeout_ms);
extern int  tk_seek(tk_handle_t h, const char *topic, int32_t partition,
                    int64_t offset);
extern int  tk_create_topic(tk_handle_t h, const char *topic,
                            int num_partitions, int timeout_ms);
extern int  tk_delete_topic(tk_handle_t h, const char *topic,
                            int timeout_ms);
extern void tk_msg_free(tk_msg_t *m);
extern int  tk_mock_bootstrap(tk_handle_t h, char *buf, int size);
extern void tk_destroy(tk_handle_t h);

/* --- introspection & offset queries (reference rdkafka.h:
 *     rd_kafka_version_str, rd_kafka_err2str,
 *     rd_kafka_query_watermark_offsets, rd_kafka_offsets_for_times,
 *     rd_kafka_position, rd_kafka_pause/resume_partitions,
 *     rd_kafka_purge, rd_kafka_metadata, rd_kafka_conf_dump) --- */
extern int  tk_version(char *buf, int size);
extern int  tk_err2str(int err, char *buf, int size);
extern int  tk_query_watermark_offsets(tk_handle_t h, const char *topic,
                                       int32_t partition, int64_t *lo,
                                       int64_t *hi, int timeout_ms);
extern long long tk_offsets_for_times(tk_handle_t h, const char *topic,
                                      int32_t partition, int64_t ts_ms,
                                      int timeout_ms);
extern long long tk_position(tk_handle_t h, const char *topic,
                             int32_t partition);
extern int  tk_pause(tk_handle_t h, const char *topic, int32_t partition);
extern int  tk_resume(tk_handle_t h, const char *topic, int32_t partition);
extern int  tk_purge(tk_handle_t h, int in_queue, int in_flight);
extern int  tk_metadata_json(tk_handle_t h, char *buf, int size,
                             int timeout_ms);
extern int  tk_conf_dump_json(tk_handle_t h, char *buf, int size);

/* --- r5: callbacks, per-property conf, admin breadth (reference
 *     rdkafka.h: conf_set/conf_get, log/error/stats callbacks,
 *     DescribeConfigs/AlterConfigs/CreatePartitions, ListGroups/
 *     DescribeGroups) --- */
extern int  tk_set_log_cb(tk_handle_t h, tk_log_cb_t cb);
extern int  tk_set_error_cb(tk_handle_t h, tk_error_cb_t cb);
extern int  tk_set_stats_cb(tk_handle_t h, tk_stats_cb_t cb);
extern int  tk_conf_set(tk_handle_t h, const char *name,
                        const char *value);
extern int  tk_conf_get(tk_handle_t h, const char *name,
                        char *buf, int size);
/* restype: 2 = TOPIC, 4 = BROKER, 3 = GROUP (reference
 * rd_kafka_ResourceType_t). describe fills JSON {name: value}. */
extern int  tk_describe_configs(tk_handle_t h, int restype,
                                const char *name, char *buf, int size,
                                int timeout_ms);
extern int  tk_alter_configs(tk_handle_t h, int restype,
                             const char *name, const char *conf_json,
                             int timeout_ms);
extern int  tk_create_partitions(tk_handle_t h, const char *topic,
                                 int new_total, int timeout_ms);
/* JSON [[group_id, protocol_type], ...] */
extern int  tk_list_groups(tk_handle_t h, char *buf, int size,
                           int timeout_ms);
/* JSON {state, protocol_type, protocol, members: [...]} */
extern int  tk_describe_group(tk_handle_t h, const char *group,
                              char *buf, int size, int timeout_ms);
extern int  tk_delete_group(tk_handle_t h, const char *group,
                            int timeout_ms);
"""

CDEF = TYPES + FUNCS

INIT = r"""
import json
import threading

from librdkafka_tpu import Producer, Consumer
from tkafka_cffi import ffi  # noqa: F401  (the cffi embedding module)

_handles = {}
_next = [1]
_lock = threading.Lock()


def _register(obj):
    with _lock:
        h = _next[0]
        _next[0] += 1
        _handles[h] = obj
    return h


def _fail(errstr, errstr_size, exc):
    msg = str(exc).encode()[: max(0, errstr_size - 1)]
    if errstr != ffi.NULL and errstr_size > 0:
        buf = ffi.buffer(errstr, errstr_size)
        buf[: len(msg)] = msg
        buf[len(msg)] = b"\0"
    return 0


@ffi.def_extern()
def tk_producer_new(conf_json, errstr, errstr_size):
    try:
        conf = json.loads(ffi.string(conf_json).decode())
        return _register(Producer(conf))
    except Exception as e:
        return _fail(errstr, errstr_size, e)


@ffi.def_extern()
def tk_consumer_new(conf_json, errstr, errstr_size):
    try:
        conf = json.loads(ffi.string(conf_json).decode())
        return _register(Consumer(conf))
    except Exception as e:
        return _fail(errstr, errstr_size, e)


@ffi.def_extern()
def tk_produce(h, topic, partition, key, key_len, payload, length):
    p = _handles.get(h)
    if p is None:
        return -1
    try:
        p.produce(ffi.string(topic).decode(),
                  value=bytes(ffi.buffer(payload, length))
                  if payload != ffi.NULL else None,
                  key=bytes(ffi.buffer(key, key_len))
                  if key != ffi.NULL else None,
                  partition=partition)
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_flush(h, timeout_ms):
    p = _handles.get(h)
    if p is None:
        return -1
    try:
        return int(p.flush(timeout_ms / 1000.0))
    except Exception:
        return -1


_dr_cbs = {}     # handle -> C function pointer (tk_dr_cb_t)


@ffi.def_extern()
def tk_set_dr_cb(h, cb):
    if _handles.get(h) is None:
        return -1
    _dr_cbs[h] = cb
    return 0


def _dr_trampoline(h, opaque):
    cb = _dr_cbs.get(h)
    if cb is None or cb == ffi.NULL:
        return None

    def on_delivery(err, m, _cb=cb, _op=opaque):
        _cb(_op, int(err.code) if err is not None else 0,
            m.partition, m.offset if m.offset is not None else -1)
    return on_delivery


@ffi.def_extern()
def tk_produce2(h, topic, partition, key, key_len, payload, length,
                timestamp_ms, hdr_names, hdr_vals, hdr_val_lens,
                hdr_cnt, opaque):
    # produce with headers / timestamp / per-message opaque + DR
    # callback (reference rd_kafka_producev with RD_KAFKA_V_HEADER /
    # V_OPAQUE / V_TIMESTAMP).
    p = _handles.get(h)
    if p is None:
        return -1
    try:
        headers = []
        for i in range(hdr_cnt):
            name = ffi.string(hdr_names[i]).decode()
            if hdr_vals[i] == ffi.NULL:
                headers.append((name, None))
            else:
                headers.append((name, bytes(
                    ffi.buffer(hdr_vals[i], hdr_val_lens[i]))))
        p.produce(ffi.string(topic).decode(),
                  value=bytes(ffi.buffer(payload, length))
                  if payload != ffi.NULL else None,
                  key=bytes(ffi.buffer(key, key_len))
                  if key != ffi.NULL else None,
                  partition=partition,
                  timestamp=int(timestamp_ms) if timestamp_ms > 0 else 0,
                  headers=headers,
                  on_delivery=_dr_trampoline(h, opaque))
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_produce_batch(h, topic, partition, base, klens, vlens, count):
    # Arena-layout batch produce: base = concatenated key||value
    # bytes, klens/vlens int32 arrays (-1 = null) -- the same memory
    # layout the enqueue lane's Arena uses internally, so the whole run
    # appends in ONE native pass (reference rd_kafka_produce_batch,
    # rdkafka_msg.c:478). Returns records enqueued.
    p = _handles.get(h)
    if p is None:
        return -1
    done = 0
    try:
        t = ffi.string(topic).decode()
        lane = p._rk._lane
        raw = getattr(lane, "produce_raw", None)
        import numpy as _np
        ka = _np.frombuffer(bytes(ffi.buffer(klens, count * 4)),
                            dtype=_np.int32)
        va = _np.frombuffer(bytes(ffi.buffer(vlens, count * 4)),
                            dtype=_np.int32)
        total = int((_np.where(ka > 0, ka, 0)
                     + _np.where(va > 0, va, 0)).sum())
        blob = None   # copied lazily: the raw() lane reads base in place
        off = 0
        while done < count:
            if raw is not None:
                n = raw(t, int(partition),
                        int(ffi.cast("intptr_t", base)) + off,
                        int(ffi.cast("intptr_t", klens)) + done * 4,
                        int(ffi.cast("intptr_t", vlens)) + done * 4,
                        count - done)
                if n > 0:
                    for i in range(done, done + n):
                        off += (ka[i] if ka[i] > 0 else 0) \
                            + (va[i] if va[i] > 0 else 0)
                    done += n
                    continue
            # first-sight (toppar not registered) or ineligible: route
            # ONE record through the Python path, then retry the lane
            if blob is None:
                blob = bytes(ffi.buffer(base, total))
            kl, vl = int(ka[done]), int(va[done])
            k = blob[off:off + kl] if kl >= 0 else None
            off += max(kl, 0)
            v = blob[off:off + vl] if vl >= 0 else None
            off += max(vl, 0)
            p.produce(t, value=v, key=k, partition=int(partition))
            done += 1
        return done
    except Exception:
        return done    # records enqueued before the failure


@ffi.def_extern()
def tk_poll(h, timeout_ms):
    # Serve the handle's reply queue (DR trampolines fire here or in
    # tk_flush; reference rd_kafka_poll). On a consumer handle this
    # serves the NON-message ops (errors/stats) like rd_kafka_poll on a
    # consumer -- messages come via tk_consumer_poll.
    obj = _handles.get(h)
    if obj is None:
        return -1
    try:
        if isinstance(obj, Consumer):
            return int(obj.poll_kafka(timeout_ms / 1000.0))
        return int(obj.poll(timeout_ms / 1000.0))
    except Exception:
        return -1


@ffi.def_extern()
def tk_outq_len(h):
    obj = _handles.get(h)
    if obj is None:
        return -1
    try:
        return len(obj)
    except Exception:
        return -1


@ffi.def_extern()
def tk_assign(h, topic, partitions, offsets, nparts):
    # Simple-consumer assignment with optional start offsets
    # (reference rd_kafka_assign; offsets NULL or -1001 = stored/auto).
    c = _handles.get(h)
    if c is None:
        return -1
    try:
        from librdkafka_tpu.client.consumer import TopicPartition
        t = ffi.string(topic).decode()
        tps = []
        for i in range(nparts):
            off = -1001 if offsets == ffi.NULL else int(offsets[i])
            tps.append(TopicPartition(t, int(partitions[i]), off))
        c.assign(tps)
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_unassign(h):
    c = _handles.get(h)
    if c is None:
        return -1
    try:
        c.unassign()
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_commit(h, async_flag):
    c = _handles.get(h)
    if c is None:
        return -1
    try:
        c.commit(asynchronous=bool(async_flag))
        return 0
    except Exception:
        return -2


@ffi.def_extern()
def tk_committed(h, topic, partition, timeout_ms):
    # Committed offset for one partition; -1001 = none, < -2000000 =
    # error (code folded in).
    c = _handles.get(h)
    if c is None:
        return -2000001
    try:
        from librdkafka_tpu.client.consumer import TopicPartition
        tp = TopicPartition(ffi.string(topic).decode(), int(partition))
        res = c.committed([tp], timeout=timeout_ms / 1000.0)
        return int(res[0].offset)
    except Exception:
        return -2000002


@ffi.def_extern()
def tk_seek(h, topic, partition, offset):
    c = _handles.get(h)
    if c is None:
        return -1
    try:
        from librdkafka_tpu.client.consumer import TopicPartition
        c.seek(TopicPartition(ffi.string(topic).decode(),
                              int(partition), int(offset)))
        return 0
    except Exception:
        return -1


def _admin_for(h):
    # Lazy AdminClient against the handle's cluster (its in-process
    # mock, or its bootstrap.servers).
    obj = _handles.get(h)
    if obj is None:
        return None
    a = getattr(obj, "_tk_admin", None)
    if a is None:
        from librdkafka_tpu.client.admin import AdminClient
        cluster = getattr(obj._rk, "mock_cluster", None)
        bs = (cluster.bootstrap_servers() if cluster is not None
              else obj._rk.conf.get("bootstrap.servers"))
        a = AdminClient({"bootstrap.servers": bs})
        obj._tk_admin = a
    return a


@ffi.def_extern()
def tk_create_topic(h, topic, num_partitions, timeout_ms):
    try:
        a = _admin_for(h)
        if a is None:
            return -1
        from librdkafka_tpu.client.admin import NewTopic
        futs = a.create_topics(
            [NewTopic(ffi.string(topic).decode(),
                      num_partitions=num_partitions)],
            operation_timeout=timeout_ms / 1000.0)
        for f in futs.values():
            f.result(timeout_ms / 1000.0)
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_delete_topic(h, topic, timeout_ms):
    try:
        a = _admin_for(h)
        if a is None:
            return -1
        futs = a.delete_topics([ffi.string(topic).decode()],
                               operation_timeout=timeout_ms / 1000.0)
        for f in futs.values():
            f.result(timeout_ms / 1000.0)
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_subscribe(h, topics_csv):
    c = _handles.get(h)
    if c is None:
        return -1
    try:
        c.subscribe([t.strip() for t
                     in ffi.string(topics_csv).decode().split(",")
                     if t.strip()])
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_consumer_poll(h, timeout_ms, out):
    # 1 = message filled into out, 0 = nothing, <0 = error.
    # The caller's stack struct starts as garbage: initialize EVERY
    # field before any early return.
    out.err = 0
    out.topic = ffi.NULL
    out.key = ffi.NULL
    out.payload = ffi.NULL
    out.hdr_cnt = 0
    out.hdr_names = ffi.NULL
    out.hdr_vals = ffi.NULL
    out.hdr_val_lens = ffi.NULL
    out.key_len = 0
    out.len = 0
    out.partition = -1
    out.offset = -1
    out.timestamp = -1
    c = _handles.get(h)
    if c is None:
        return -1
    try:
        m = c.poll(timeout_ms / 1000.0)
    except Exception:
        return -2        # cffi's default-0 would read as "no message"
    if m is None:
        return 0
    if m.error is not None:
        out.err = int(m.error.code)
        return 1
    t = (m.topic or "").encode()
    out.topic = lib_strdup(t)
    out.partition = m.partition
    out.offset = m.offset
    out.timestamp = m.timestamp if m.timestamp else -1
    if m.key is None:
        out.key = ffi.NULL
        out.key_len = 0
    else:
        out.key = lib_memdup(m.key)
        out.key_len = len(m.key)
    if m.value is None:
        out.payload = ffi.NULL
        out.len = 0
    else:
        out.payload = lib_memdup(m.value)
        out.len = len(m.value)
    hs = m.headers
    if hs:
        # first-class header arrays, raw byte values (reference
        # rd_kafka_header_get_all — no JSON, no escaping)
        n = len(hs)
        names = ffi.new("char*[]", n)
        vals = ffi.new("char*[]", n)
        lens = ffi.new("size_t[]", n)
        for i, (hk, hv) in enumerate(hs):
            names[i] = lib_strdup(hk.encode())
            if hv is None:
                vals[i] = ffi.NULL
                lens[i] = 0
            else:
                vals[i] = lib_memdup(hv)
                lens[i] = len(hv)
        out.hdr_cnt = n
        out.hdr_names = _track(names)
        out.hdr_vals = _track(vals)
        out.hdr_val_lens = _track(lens)
    return 1


_allocs = {}


def lib_memdup(b):
    buf = ffi.new("char[]", bytes(b))
    _allocs[int(ffi.cast("intptr_t", buf))] = buf
    return buf


def lib_strdup(b):
    buf = ffi.new("char[]", bytes(b) + b"\0")
    _allocs[int(ffi.cast("intptr_t", buf))] = buf
    return buf


def _track(cdata):
    _allocs[int(ffi.cast("intptr_t", cdata))] = cdata
    return cdata


def _release(ptr):
    if ptr != ffi.NULL:
        _allocs.pop(int(ffi.cast("intptr_t", ptr)), None)


@ffi.def_extern()
def tk_msg_free(m):
    _release(m.topic)
    _release(m.key)
    _release(m.payload)
    for i in range(m.hdr_cnt):
        if m.hdr_names != ffi.NULL:
            _release(m.hdr_names[i])
        if m.hdr_vals != ffi.NULL:
            _release(m.hdr_vals[i])
    _release(ffi.cast("char *", m.hdr_names))
    _release(ffi.cast("char *", m.hdr_vals))
    _release(ffi.cast("char *", m.hdr_val_lens))
    m.topic = m.key = m.payload = ffi.NULL
    m.hdr_names = m.hdr_vals = ffi.NULL
    m.hdr_val_lens = ffi.NULL
    m.hdr_cnt = 0


@ffi.def_extern()
def tk_mock_bootstrap(h, buf, size):
    # bootstrap.servers of the handle's in-process mock cluster
    # (test.mock.num.brokers), for wiring a second client to it
    obj = _handles.get(h)
    if obj is None:
        return -1
    cluster = getattr(obj._rk, "mock_cluster", None)
    if cluster is None:
        return -1
    bs = cluster.bootstrap_servers().encode()
    if len(bs) + 1 > size:
        return -1
    b = ffi.buffer(buf, size)
    b[: len(bs)] = bs
    b[len(bs)] = b"\0"
    return len(bs)


@ffi.def_extern()
def tk_destroy(h):
    obj = _handles.pop(h, None)
    _dr_cbs.pop(h, None)   # handle ids are never reused: drop the DR
                           # trampoline or registrations leak forever
    for kind in ("log", "err", "stats"):
        _obs_cbs.pop((h, kind), None)
    if obj is not None:
        try:
            obj.close()
        except Exception:
            pass


def _write_cstr(buf, size, s):
    b = s.encode() if isinstance(s, str) else bytes(s)
    if buf == ffi.NULL or size <= 0 or len(b) + 1 > size:
        return -1
    out = ffi.buffer(buf, size)
    out[: len(b)] = b
    out[len(b)] = b"\0"
    return len(b)


@ffi.def_extern()
def tk_version(buf, size):
    # reference: rd_kafka_version_str()
    import librdkafka_tpu
    return _write_cstr(buf, size, librdkafka_tpu.__version__)


@ffi.def_extern()
def tk_err2str(err, buf, size):
    # reference: rd_kafka_err2str / rd_kafka_err2name
    from librdkafka_tpu.client.errors import Err
    try:
        name = Err(err).name
    except ValueError:
        name = f"UNKNOWN_ERR_{err}"
    return _write_cstr(buf, size, name)


@ffi.def_extern()
def tk_query_watermark_offsets(h, topic, partition, lo, hi, timeout_ms):
    # reference: rd_kafka_query_watermark_offsets (consumer handles)
    from librdkafka_tpu.client.consumer import TopicPartition
    c = _handles.get(h)
    if not isinstance(c, Consumer):
        return -1
    try:
        low, high = c.get_watermark_offsets(
            TopicPartition(ffi.string(topic).decode(), partition),
            timeout=timeout_ms / 1000.0)
        lo[0] = int(low)
        hi[0] = int(high)
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_offsets_for_times(h, topic, partition, ts_ms, timeout_ms):
    # reference: rd_kafka_offsets_for_times; returns the offset, -1 =
    # timestamp past log end (reference semantics), -2 = error
    from librdkafka_tpu.client.consumer import TopicPartition
    c = _handles.get(h)
    if not isinstance(c, Consumer):
        return -2
    try:
        out = c.offsets_for_times(
            [TopicPartition(ffi.string(topic).decode(), partition,
                            ts_ms)],
            timeout=timeout_ms / 1000.0)
        return int(out[0].offset)
    except Exception:
        return -2


@ffi.def_extern()
def tk_position(h, topic, partition):
    # reference: rd_kafka_position; next offset to consume, -1001 when
    # the partition is not assigned/positioned
    from librdkafka_tpu.client.consumer import TopicPartition
    c = _handles.get(h)
    if not isinstance(c, Consumer):
        return -1001
    try:
        out = c.position(
            [TopicPartition(ffi.string(topic).decode(), partition)])
        return int(out[0].offset)
    except Exception:
        return -1001


@ffi.def_extern()
def tk_pause(h, topic, partition):
    from librdkafka_tpu.client.consumer import TopicPartition
    c = _handles.get(h)
    if not isinstance(c, Consumer):
        return -1
    try:
        c.pause([TopicPartition(ffi.string(topic).decode(), partition)])
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_resume(h, topic, partition):
    from librdkafka_tpu.client.consumer import TopicPartition
    c = _handles.get(h)
    if not isinstance(c, Consumer):
        return -1
    try:
        c.resume([TopicPartition(ffi.string(topic).decode(), partition)])
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_purge(h, in_queue, in_flight):
    # reference: rd_kafka_purge (producer handles)
    p = _handles.get(h)
    if not isinstance(p, Producer):
        return -1
    try:
        p.purge(in_queue=bool(in_queue), in_flight=bool(in_flight))
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_metadata_json(h, buf, size, timeout_ms):
    # reference: rd_kafka_metadata, flattened to JSON for C callers:
    # {"brokers": {id: "host:port"}, "controller_id": n,
    #  "topics": {name: {partition: leader}}}
    obj = _handles.get(h)
    if obj is None:
        return -1
    rk = obj._rk
    try:
        rk.metadata_refresh("tk_metadata")
        # producer/consumer handles refresh SPARSELY (their known
        # topics), so waiting for a FULL enumeration would never
        # resolve — a warm cache (>=1 broker) is the reference's
        # rd_kafka_metadata(all_topics=0) behavior
        if not rk.metadata_wait(lambda: rk.metadata["brokers"],
                                timeout_ms / 1000.0):
            return -1
        with rk._metadata_lock:
            md = rk.metadata
            snap = {"brokers": {str(i): f"{b[0]}:{b[1]}"
                                if isinstance(b, (tuple, list)) else str(b)
                                for i, b in md["brokers"].items()},
                    "controller_id": md.get("controller_id", -1),
                    "topics": {t: {str(p): ldr for p, ldr in ps.items()}
                               for t, ps in md["topics"].items()}}
        return _write_cstr(buf, size, json.dumps(snap))
    except Exception:
        return -1


@ffi.def_extern()
def tk_conf_dump_json(h, buf, size):
    # reference: rd_kafka_conf_dump — the handle's effective conf
    obj = _handles.get(h)
    if obj is None:
        return -1
    try:
        d = obj._rk.conf.dump()
        safe = {k: (v if isinstance(v, (str, int, float, bool,
                                        type(None))) else repr(v))
                for k, v in d.items()}
        return _write_cstr(buf, size, json.dumps(safe))
    except Exception:
        return -1


# ---- r5: observability callbacks, per-property conf, admin breadth ----

_obs_cbs = {}     # (handle, kind) -> C function pointer


@ffi.def_extern()
def tk_set_log_cb(h, cb):
    obj = _handles.get(h)
    if obj is None:
        return -1
    _obs_cbs[(h, "log")] = cb

    from librdkafka_tpu.client.kafka import Kafka as _K

    def log_cb(level, fac, msg, _h=h):
        c = _obs_cbs.get((_h, "log"))
        if c is None or c == ffi.NULL:
            return
        lv = (level if isinstance(level, int)
              else _K._LOG_LEVELS.get(level, 6))
        c(lv, ffi.new("char[]", str(fac).encode() + b"\0"),
          ffi.new("char[]", str(msg).encode() + b"\0"))
    obj._rk.conf.set("log_cb", log_cb)
    obj._rk.log_cb = log_cb        # live handles read the cached ref
    return 0


@ffi.def_extern()
def tk_set_error_cb(h, cb):
    obj = _handles.get(h)
    if obj is None:
        return -1
    _obs_cbs[(h, "err")] = cb

    def error_cb(err, _h=h):
        c = _obs_cbs.get((_h, "err"))
        if c is None or c == ffi.NULL:
            return
        c(int(err.code), ffi.new("char[]", str(err).encode() + b"\0"))
    obj._rk.conf.set("error_cb", error_cb)
    return 0


@ffi.def_extern()
def tk_set_stats_cb(h, cb):
    # fires from tk_poll/tk_flush once statistics.interval.ms elapses
    # (set it in conf_json at creation, or via tk_conf_set)
    obj = _handles.get(h)
    if obj is None:
        return -1
    _obs_cbs[(h, "stats")] = cb

    def stats_cb(blob, _h=h):
        c = _obs_cbs.get((_h, "stats"))
        if c is None or c == ffi.NULL:
            return
        c(ffi.new("char[]", blob.encode() + b"\0"))
    obj._rk.conf.set("stats_cb", stats_cb)
    return 0


@ffi.def_extern()
def tk_conf_set(h, name, value):
    # per-property set on the live handle (reference rd_kafka_conf_set;
    # post-creation mutation revalidates cached eligibility decisions
    # through the conf listeners)
    obj = _handles.get(h)
    if obj is None:
        return -1
    try:
        obj._rk.conf.set(ffi.string(name).decode(),
                         ffi.string(value).decode())
        return 0
    except Exception:
        return -2


@ffi.def_extern()
def tk_conf_get(h, name, buf, size):
    obj = _handles.get(h)
    if obj is None:
        return -1
    try:
        v = obj._rk.conf.get(ffi.string(name).decode())
        if isinstance(v, bool):
            v = "true" if v else "false"
        return _write_cstr(buf, size, str(v))
    except Exception:
        return -2


def _restype_obj(restype, name):
    from librdkafka_tpu.client.admin import ConfigResource
    return ConfigResource(int(restype), ffi.string(name).decode())


@ffi.def_extern()
def tk_describe_configs(h, restype, name, buf, size, timeout_ms):
    try:
        a = _admin_for(h)
        if a is None:
            return -1
        r = _restype_obj(restype, name)
        futs = a.describe_configs([r],
                                  operation_timeout=timeout_ms / 1000.0)
        entries = futs[r].result(timeout_ms / 1000.0)
        return _write_cstr(buf, size, json.dumps(
            {n: e.value for n, e in entries.items()}))
    except Exception:
        return -1


@ffi.def_extern()
def tk_alter_configs(h, restype, name, conf_json, timeout_ms):
    try:
        a = _admin_for(h)
        if a is None:
            return -1
        r = _restype_obj(restype, name)
        for k, v in json.loads(ffi.string(conf_json).decode()).items():
            r.set_config(k, v)
        futs = a.alter_configs([r],
                               operation_timeout=timeout_ms / 1000.0)
        futs[r].result(timeout_ms / 1000.0)
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_create_partitions(h, topic, new_total, timeout_ms):
    try:
        a = _admin_for(h)
        if a is None:
            return -1
        from librdkafka_tpu.client.admin import NewPartitions
        futs = a.create_partitions(
            [NewPartitions(ffi.string(topic).decode(), int(new_total))],
            operation_timeout=timeout_ms / 1000.0)
        for f in futs.values():
            f.result(timeout_ms / 1000.0)
        return 0
    except Exception:
        return -1


@ffi.def_extern()
def tk_list_groups(h, buf, size, timeout_ms):
    try:
        a = _admin_for(h)
        if a is None:
            return -1
        fut = a.list_groups(operation_timeout=timeout_ms / 1000.0)
        return _write_cstr(buf, size,
                           json.dumps(fut.result(timeout_ms / 1000.0)))
    except Exception:
        return -1


def _jsonable(v):
    if isinstance(v, bytes):
        return v.decode("latin-1")
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


@ffi.def_extern()
def tk_describe_group(h, group, buf, size, timeout_ms):
    try:
        a = _admin_for(h)
        if a is None:
            return -1
        g = ffi.string(group).decode()
        futs = a.describe_groups([g],
                                 operation_timeout=timeout_ms / 1000.0)
        info = futs[g].result(timeout_ms / 1000.0)
        return _write_cstr(buf, size, json.dumps(_jsonable(info)))
    except Exception:
        return -1


@ffi.def_extern()
def tk_delete_group(h, group, timeout_ms):
    try:
        a = _admin_for(h)
        if a is None:
            return -1
        g = ffi.string(group).decode()
        futs = a.delete_groups([g], operation_timeout=timeout_ms / 1000.0)
        futs[g].result(timeout_ms / 1000.0)
        return 0
    except Exception:
        return -1
"""

HEADER_TEXT = (
    "/* tkafka.h — C API for the librdkafka_tpu framework\n"
    " * (the rebuild's src-cpp/ equivalent: a second-language binding\n"
    " * over the same core; reference surface: src/rdkafka.h).\n"
    " * Link: -ltkafka  (plus the embedded CPython the .so carries). */\n"
    "#pragma once\n"
    "#include <stdint.h>\n"
    "#include <stddef.h>\n"
    "#ifdef __cplusplus\nextern \"C\" {\n#endif\n"
    + CDEF +
    "#ifdef __cplusplus\n}\n#endif\n")


def build(force: bool = False) -> str:
    if not force and os.path.exists(SO) and os.path.exists(HEADER) \
            and os.path.getmtime(SO) >= os.path.getmtime(__file__) \
            and os.path.getmtime(HEADER) >= os.path.getmtime(__file__):
        return SO
    import cffi
    ffibuilder = cffi.FFI()
    ffibuilder.embedding_api(CDEF)
    # the cdef'd types must exist in the generated C too
    ffibuilder.set_source("tkafka_cffi", TYPES)
    ffibuilder.embedding_init_code(INIT)
    ffibuilder.compile(tmpdir=HERE, target=SO, verbose=False)
    with open(HEADER, "w") as f:
        f.write(HEADER_TEXT)
    return SO


if __name__ == "__main__":
    print(build(force=True))
