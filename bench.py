#!/usr/bin/env python
"""Driver benchmark — the BASELINE.json codec-offload seam, measured
honestly for the environment it runs in.

Metric of record: CRC32C of 128 concurrent 64KB partition batches — the
MessageSet v2 checksum hot loop (reference crc32c.c:39, called per batch
at rdkafka_msgset_writer.c:1230) — TPU device time for the one-matmul
GF(2) MXU kernel (ops/crc32c_jax.py) vs the native CPU provider
(ops/native/codec.cpp tk_crc32c_many) on the same blocks.

Why device time: this dev environment reaches its single v5e chip
through an "axon" tunnel whose measured transport is 2-3 MB/s with
~100 ms round-trip latency (PERF.md).  Every synchronous host<->device
offload is transport-bound at ~3 orders of magnitude below PCIe, so
end-to-end offload throughput here measures the tunnel, not the design.
Device time is what transfers to real TPU-VM hardware; the transport
probe and the host-pipeline number are reported alongside so nothing is
hidden.  vs_baseline = tpu_device_rate / cpu_rate (bit-exact outputs,
asserted).

Also reported (extras in the same JSON line):
  host_pipeline_msgs_s  - end-to-end producer msgs/s, 1KB lz4 msgs,
                          16 partitions, external mock broker process
                          (the rdkafka_performance -P analog)
  lz4_device_ms         - TPU lz4 block-encoder device time, 4x64KB
                          (gather-bound; see PERF.md for why wire-exact
                          LZ4 cannot win on TPU vector hardware)
  transport_mb_s        - measured host->device bandwidth
Env knobs: BENCH_MSGS (500000), BENCH_MSG_SIZE (1024), BENCH_TOPPARS (16).
"""
import json
import os
import sys
import time

import numpy as np


def _json_path():
    """--json <path>: also write the leg's JSON summary to a file, so
    the BENCH_r*.json trajectory is a machine-written artifact instead
    of hand-assembled terminal scrapes."""
    if "--json" in sys.argv:
        i = sys.argv.index("--json")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("--"):
            raise SystemExit("--json requires a file path")
        return sys.argv[i + 1]
    return None


def _emit(obj: dict) -> None:
    """Print the leg summary AND write it to the --json artifact;
    every artifact carries the unified metrics-registry snapshot
    (versioned — obs.schema) and the SLO legs append one trend row."""
    from librdkafka_tpu.obs import metrics as _obs_metrics
    obj.setdefault("obs", _obs_metrics.snapshot())
    line = json.dumps(obj)
    print(line)
    path = _json_path()
    if path:
        with open(path, "w") as f:
            f.write(line + "\n")
    try:
        _trend_append(obj)
    except Exception as e:   # the ledger must never fail a bench run
        print(f"trend append failed: {e!r}", file=sys.stderr)


#: trend-ledger row schema (scripts/trendgate.py checks this)
TREND_SCHEMA = 1


def _trend_path() -> str:
    return os.environ.get("BENCH_TREND_PATH") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_TREND.jsonl")


def _trend_leg() -> "str | None":
    """The ledger leg id for this invocation (None = leg not tracked)."""
    smoke = "--smoke" in sys.argv
    if "--fleet" in sys.argv:
        return "fleet_smoke" if smoke else "fleet"
    if "--chaos" in sys.argv:
        return "chaos"
    if "--partitions" in sys.argv:
        return "partitions_smoke" if smoke else "partitions"
    if smoke:
        return "smoke"
    return None


def _trend_metrics(leg: str, obj: dict) -> dict:
    """Headline SLO metrics for one leg's artifact, each tagged with
    its good direction ("higher" rates, "lower" latencies) so the gate
    knows which way a delta regresses."""
    def pick(*specs):
        out = {}
        for name, val, direction in specs:
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                out[name] = {"v": float(val), "dir": direction}
        return out

    if leg == "smoke":
        ovh = obj.get("trace_overhead") or {}
        return pick(
            ("produce_ns_per_msg", ovh.get("produce_ns_per_msg"), "lower"),
            ("obs_overhead_pct", ovh.get("combined_overhead_pct",
                                         ovh.get("overhead_pct")), "lower"),
            ("elapsed_s", obj.get("elapsed_s"), "lower"))
    if leg in ("fleet", "fleet_smoke"):
        return pick(
            ("fleet_msgs_s", obj.get("fleet_msgs_s"), "higher"),
            ("client_p99_ms_max", obj.get("client_p99_ms_max"), "lower"),
            ("recovery_p99_ms", obj.get("recovery_p99_ms"), "lower"),
            ("converged_s", obj.get("converged_s"), "lower"))
    if leg == "chaos":
        return pick(
            ("storm_msgs_s", obj.get("storm_msgs_s"), "higher"),
            ("recovery_p50_ms", obj.get("recovery_p50_ms"), "lower"),
            ("recovery_p99_ms", obj.get("recovery_p99_ms"), "lower"))
    if leg in ("partitions", "partitions_smoke"):
        scale = obj.get("scale") or {}
        big = scale.get(max(scale, key=int)) if scale else {}
        return pick(
            ("wire_reduction", obj.get("wire_reduction"), "higher"),
            ("stats_emit_flatness",
             obj.get("stats_emit_flatness"), "lower"),
            ("produce_msgs_s", big.get("produce_msgs_s"), "higher"),
            ("stats_emit_ms", big.get("stats_emit_ms"), "lower"))
    return {}


def _git_rev() -> str:
    import subprocess
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def _trend_append(obj: dict) -> None:
    """One ledger row per SLO leg run (ISSUE 20): the persistent
    BENCH_TREND.jsonl trend that scripts/trendgate.py gates on.
    ``--anchor`` marks the row as the new comparison baseline."""
    leg = _trend_leg()
    if leg is None:
        return
    metrics = _trend_metrics(leg, obj)
    if not metrics:
        return
    row = {"schema": TREND_SCHEMA,
           "rev": _git_rev(),
           "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "leg": leg,
           "anchor": "--anchor" in sys.argv,
           "ok": obj.get("ok", True),
           "metrics": metrics}
    with open(_trend_path(), "a") as f:
        f.write(json.dumps(row) + "\n")
    print(f"trend: appended {leg} row ({', '.join(metrics)}) -> "
          f"{_trend_path()}", file=sys.stderr)


def _gcd(a: int, b: int) -> int:
    while b:
        a, b = b, a % b
    return a


def _payloads(n: int, size: int) -> list[bytes]:
    out = []
    base = (b'{"seq": %07d, "user": "u%05d", "event": "click", '
            b'"props": "abcdefghijklmnopqrstuvwxyz0123456789"}')
    for i in range(n):
        b = base % (i, i % 1000)
        out.append((b * (size // len(b) + 1))[:size])
    return out


_MOCK_PROC = None
_MOCK_BS = None


def _external_mock(toppars: int) -> str:
    """Mock cluster in its OWN process (librdkafka_tpu.mock.standalone)
    — the role a real broker plays for rdkafka_performance. An
    in-process mock shares the client's GIL, so its request parsing
    counts against the client and understates the pipeline by ~40%
    (measured 77k vs 129k msgs/s, 1KB lz4)."""
    global _MOCK_PROC, _MOCK_BS
    if _MOCK_BS is None:
        import select
        import subprocess
        import tempfile
        # stderr goes to a FILE, not a PIPE: a pipe nobody drains fills
        # its ~64KB buffer and blocks the mock mid-benchmark; the file is
        # read back only on startup failure.
        errf = tempfile.NamedTemporaryFile(
            mode="w+", prefix="tk_mock_err_", suffix=".log", delete=False)
        _MOCK_PROC = subprocess.Popen(
            [sys.executable, "-m", "librdkafka_tpu.mock.standalone",
             "--brokers", "2", "--partitions", str(toppars),
             # cap the mock's log so 6 interleaved trials don't grow the
             # broker process unboundedly (memory pressure slows later
             # trials and biases the cpu-vs-tpu comparison)
             "--retention-mb", "32"],
            stdout=subprocess.PIPE, stderr=errf, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        # guard the address read: if the child neither prints nor exits,
        # readline() would block the whole bench forever
        r, _, _ = select.select([_MOCK_PROC.stdout], [], [], 30.0)
        line = _MOCK_PROC.stdout.readline().strip() if r else ""
        if not line:        # child died (or hung) before its address
            _MOCK_PROC.kill()
            errf.flush()
            err = open(errf.name).read()
            errf.close()
            raise RuntimeError(f"standalone mock failed to start: {err}")
        # success: the mock inherited the fd; drop ours and the name —
        # warnings it writes later just go to the (unlinked) file
        errf.close()
        os.unlink(errf.name)
        _MOCK_BS = line
    return _MOCK_BS


def _reset_mock():
    """Kill the cached external mock so the next pipeline call starts a
    fresh one (e.g. with a different partition count)."""
    global _MOCK_PROC, _MOCK_BS
    if _MOCK_PROC is not None:
        _MOCK_PROC.kill()
    _MOCK_PROC = None
    _MOCK_BS = None


def host_pipeline(n_msgs: int, size: int, toppars: int,
                  backend: str = "cpu",
                  extra_conf: dict | None = None) -> float:
    """End-to-end producer msgs/s against an external mock broker
    process (the rdkafka_performance -P analog)."""
    from librdkafka_tpu import Producer

    p = Producer({
        "bootstrap.servers": _external_mock(toppars),
        "compression.backend": backend,
        "compression.codec": "lz4",
        "batch.num.messages": 10000,
        "linger.ms": 50,
        "queue.buffering.max.messages": 2_000_000,
        **(extra_conf or {}),
    })
    vals = _payloads(min(n_msgs, 4096), size)
    if backend == "tpu":
        # one-time async warmup (transport probe + any kernel compiles)
        # must not overlap the timed window
        p._rk.codec_provider.wait_warm(180.0)
    from itertools import cycle, islice

    # (value, partition) pairs cycled at C speed: the loop still calls
    # produce() once per message like rdkafka_performance's C loop
    # (examples/rdkafka_performance.c:764); only the per-iteration
    # payload/partition bookkeeping is hoisted out of Python bytecode
    pairs = [(vals[i % len(vals)], i % toppars)
             for i in range(len(vals) * toppars // _gcd(len(vals), toppars))]
    produce = p.produce
    for v, part in islice(cycle(pairs), 2000):  # warm sockets + codecs
        produce("bench", value=v, partition=part)
    if p.flush(120.0) != 0:
        raise RuntimeError("warmup flush did not drain")
    t0 = time.perf_counter()
    for v, part in islice(cycle(pairs), n_msgs):
        produce("bench", value=v, partition=part)
    if p.flush(120.0) != 0:
        raise RuntimeError("bench flush did not drain")
    rate = n_msgs / (time.perf_counter() - t0)
    p.close()
    return rate


def txn_pipeline(n_msgs: int, size: int, toppars: int,
                 mode: str = "plain", txn_size: int = 20000) -> float:
    """End-to-end producer msgs/s with the message stream chopped into
    transactions of txn_size messages (mode=commit/abort), vs the same
    produce+flush cadence on a plain idempotent producer (mode=plain).
    The flush boundary is identical across modes so the comparison
    isolates the txn machinery itself (begin, AddPartitionsToTxn,
    EndTxn markers, and for abort the KIP-360 epoch bump)."""
    from itertools import cycle, islice

    from librdkafka_tpu import Producer

    conf = {
        "bootstrap.servers": _external_mock(toppars),
        "compression.codec": "lz4",
        "batch.num.messages": 10000,
        "linger.ms": 50,
        "queue.buffering.max.messages": 2_000_000,
    }
    if mode == "plain":
        conf["enable.idempotence"] = True
    else:
        conf["transactional.id"] = f"bench-tx-{mode}"
    p = Producer(conf)
    if mode != "plain":
        p.init_transactions(60)
    vals = _payloads(min(n_msgs, 4096), size)
    pairs = [(vals[i % len(vals)], i % toppars)
             for i in range(len(vals) * toppars // _gcd(len(vals), toppars))]
    produce = p.produce
    if mode != "plain":
        p.begin_transaction()
    for v, part in islice(cycle(pairs), 2000):  # warm sockets + codecs
        produce("txbench", value=v, partition=part)
    if p.flush(120.0) != 0:
        raise RuntimeError("warmup flush did not drain")
    if mode == "commit":
        p.commit_transaction(60)
    elif mode == "abort":
        p.abort_transaction(60)
    t0 = time.perf_counter()
    it = islice(cycle(pairs), n_msgs)
    remaining = n_msgs
    while remaining:
        chunk = min(txn_size, remaining)
        if mode != "plain":
            p.begin_transaction()
        for v, part in islice(it, chunk):
            produce("txbench", value=v, partition=part)
        # every message is delivered in every mode — abort purges only
        # undelivered messages, so the flush precedes it
        if p.flush(120.0) != 0:
            raise RuntimeError("txn bench flush did not drain")
        if mode == "commit":
            p.commit_transaction(60)
        elif mode == "abort":
            p.abort_transaction(60)
        remaining -= chunk
    rate = n_msgs / (time.perf_counter() - t0)
    p.close()
    return rate


def txn_bench() -> dict:
    """bench.py --txn (ISSUE 4 acceptance): transactional produce
    throughput — commit and abort legs vs the plain idempotent
    producer at the same flush cadence, 1KB lz4. The txn machinery
    (AddPartitionsToTxn registration, EndTxn markers, abort's epoch
    bump) must cost < 15% end-to-end. Trials interleave plain/commit/
    abort so host load drift hits all three legs equally."""
    n_msgs = int(os.environ.get("BENCH_TXN_MSGS", 120000))
    size = int(os.environ.get("BENCH_MSG_SIZE", 1024))
    toppars = int(os.environ.get("BENCH_TOPPARS", 16))
    rates: dict[str, list[float]] = {"plain": [], "commit": [], "abort": []}
    for _trial in range(3):
        for mode in ("plain", "commit", "abort"):
            rates[mode].append(txn_pipeline(n_msgs, size, toppars, mode))
    med = {m: sorted(r)[1] for m, r in rates.items()}
    overhead = {m: 1.0 - med[m] / med["plain"] for m in ("commit", "abort")}
    return {
        "n_msgs": n_msgs, "msg_size": size, "toppars": toppars,
        "plain_idempotent_msgs_s": round(med["plain"]),
        "txn_commit_msgs_s": round(med["commit"]),
        "txn_abort_msgs_s": round(med["abort"]),
        "commit_overhead": round(overhead["commit"], 4),
        "abort_overhead": round(overhead["abort"], 4),
        "acceptance_overhead_lt": 0.15,
        "pass": bool(overhead["commit"] < 0.15
                     and overhead["abort"] < 0.15),
        "trials": {m: [round(x) for x in r] for m, r in rates.items()},
    }


def consumer_pipeline(n_msgs: int, size: int, toppars: int,
                      codec: str = "lz4") -> float:
    """End-to-end consumer msgs/s with check.crcs (batched fetch-side
    CRC verify + decompress; the rdkafka_performance -C analog /
    BASELINE config 4) against the external mock."""
    import time as _t

    from librdkafka_tpu import Consumer, Producer

    bs = _external_mock(toppars)
    p = Producer({"bootstrap.servers": bs, "compression.codec": codec,
                  "batch.num.messages": 10000, "linger.ms": 50,
                  "queue.buffering.max.messages": 2_000_000})
    vals = _payloads(4096, size)
    for i in range(n_msgs):
        p.produce("cbench", value=vals[i % len(vals)],
                  partition=i % toppars)
    if p.flush(120.0) != 0:
        raise RuntimeError("consumer-bench produce did not drain")
    p.close()

    c = Consumer({"bootstrap.servers": bs, "group.id": "bench-c",
                  "auto.offset.reset": "earliest", "check.crcs": True,
                  "queued.min.messages": 1000000})
    c.subscribe(["cbench"])
    # first message = assignment + fetch warmup; then time the drain
    got = 0
    deadline = _t.monotonic() + 60
    while got < 1 and _t.monotonic() < deadline:
        if c.poll(0.2) is not None:
            got = 1
    t0 = _t.perf_counter()
    while got < n_msgs and _t.monotonic() < deadline:
        m = c.poll(0.5)
        if m is not None and m.error is None:
            got += 1
    rate = (got - 1) / max(_t.perf_counter() - t0, 1e-9)
    c.close()
    if got < n_msgs:
        raise RuntimeError(f"consumer bench incomplete: {got}/{n_msgs}")
    return rate


def codec_size_sweep(toppars: int = 16) -> dict:
    """BASELINE config 3: snappy + zstd over 256B..64KB payloads,
    producer AND consumer direction (the rdkafka_performance -P/-C
    sweep, examples/rdkafka_performance.c:555-644). Message counts
    scale with size to keep each cell around 50-100 MB of payload;
    rates are one trial per cell (the table's value is the SHAPE of
    the curve)."""
    out = {}
    for codec in ("snappy", "zstd"):
        for size in (256, 1024, 16384, 65536):
            n = max(1_000, min(120_000, (48 << 20) // size))
            cell = {}
            try:
                r = host_pipeline(n, size, toppars,
                                  extra_conf={"compression.codec": codec})
                cell["producer_msgs_s"] = round(r, 1)
                cell["producer_mb_s"] = round(r * size / 1e6, 1)
            except Exception as e:
                cell["producer_msgs_s"] = None
                print(f"sweep {codec}/{size} producer: {e!r}",
                      file=sys.stderr)
            try:
                _reset_mock()
                r = consumer_pipeline(n, size, toppars, codec=codec)
                cell["consumer_msgs_s"] = round(r, 1)
                cell["consumer_mb_s"] = round(r * size / 1e6, 1)
            except Exception as e:
                cell["consumer_msgs_s"] = None
                print(f"sweep {codec}/{size} consumer: {e!r}",
                      file=sys.stderr)
            finally:
                _reset_mock()
            out[f"{codec}_{size}B"] = cell
    return out


def _sync(x) -> np.ndarray:
    """True device synchronization: a host readback (block_until_ready
    does not synchronize through the axon tunnel)."""
    return np.asarray(x)


def codec_offload():
    """CRC offload: device-time vs native CPU on 128x64KB, bit-exact.

    128 blocks is the production-representative shape — 64 concurrent
    toppars x 2 blocks each (BASELINE config 5), and exactly the MXU
    systolic tile floor (a 64-row launch leaves the array half idle;
    the provider itself pads 64+ batches up to 128, crc32c_many_mxu).
    Both providers are timed on the SAME 128 blocks.
    """
    import jax

    from librdkafka_tpu.ops import cpu
    from librdkafka_tpu.ops import crc32c_jax as cj
    from librdkafka_tpu.ops import lz4_jax
    from librdkafka_tpu.ops.packing import next_pow2, pad_left, pad_right

    B, blk = 128, cj._MXU_BLOCK
    rng = np.random.default_rng(0)
    blocks = [rng.integers(0, 256, blk, dtype=np.uint8).tobytes()
              for _ in range(B)]

    # --- CPU provider: pinned statistic (r3 verdict weak #4: the CPU
    # side swung 5.6-13.7x with shared-host load). 11 trials, report
    # BOTH the median (the loaded-host number the run actually saw) and
    # the min (the idle-host capability) so vs_baseline is attributable;
    # vs_baseline uses the MIN — the conservative comparison point.
    cpu_times = []
    for _ in range(11):
        t0 = time.perf_counter()
        ref = cpu.crc32c_many(blocks)
        cpu_times.append((time.perf_counter() - t0) * 1000)
    cpu_ms_median = sorted(cpu_times)[5]
    cpu_ms = min(cpu_times)

    # --- transport probe -------------------------------------------------
    h = np.zeros((4, blk), np.uint8)
    _sync(jax.device_put(h))                     # warm the path
    t0 = time.perf_counter()
    _sync(jax.device_put(h))
    transport_mb_s = (4 * blk / (1 << 20)) / max(time.perf_counter() - t0,
                                                 1e-9)

    # --- TPU CRC: one-matmul MXU kernel, amortized device time ----------
    Bp = B
    fn = cj._jit_mxu(Bp)
    data, lens = pad_left(blocks, blk)
    terms = np.array([cj._term_host(int(n)) for n in lens], dtype=np.uint32)
    d1 = jax.device_put(data)
    dtm = jax.device_put(terms)
    out = _sync(fn(d1, dtm))                    # compile + exactness check
    assert [int(x) for x in out.astype(np.uint32)[:B]] == list(ref), \
        "TPU CRC not bit-exact"
    t0 = time.perf_counter()
    _sync(fn(d1, dtm))
    rtt1 = (time.perf_counter() - t0) * 1000     # 1 launch + readback

    # Device time via in-graph repetition: ONE compiled call runs the
    # kernel R times under lax.fori_loop (xor-accumulated so nothing is
    # dead-code-eliminated), so the tunnel's per-dispatch cost appears
    # exactly once per measurement and cancels in the difference
    # T(R2)-T(R1). Every prior scheme (per-launch loops, two-loop
    # differencing) swung 5x run-to-run through the shared tunnel.
    import jax.numpy as jnp

    # 10 DISTINCT 8MB buffers (r4 verdict weak #1: cycling 2 distinct
    # payloads let the whole working set live in VMEM — 2 x 8MB is
    # exactly the v5e VMEM — and the "device time" beat the kernel's
    # own HBM traffic floor; with 80MB of distinct data every
    # iteration must stream from HBM)
    stack = jax.device_put(np.stack(
        [data] + [rng.integers(0, 256, data.shape, dtype=np.uint8)
                  for _ in range(9)]))           # (10, B, N)

    def make_multi(R):
        def multi(st, terms):
            def body(i, acc):
                return acc ^ fn(st[i % 10], terms)
            return jax.lax.fori_loop(0, R, body,
                                     jnp.zeros((Bp,), jnp.uint32))
        return jax.jit(multi, static_argnums=())

    m1, m2 = make_multi(2), make_multi(102)
    _sync(m1(stack, dtm)); _sync(m2(stack, dtm))     # compile both

    def timed(m):
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            _sync(m(stack, dtm))
            ts.append((time.perf_counter() - t0) * 1000)
        return sorted(ts)[2]          # median of 5

    tpu_crc_ms = max((timed(m2) - timed(m1)) / 100.0, 1e-3)

    # --- TPU lz4 block encoder: one measured launch, 4x64KB -------------
    lz4_ms = None
    try:
        lblocks = blocks[:4]
        N = next_pow2(blk)
        ldata, llens = pad_right(lblocks, N)
        lfn = lz4_jax._jit_for(N)
        ld = jax.device_put(ldata)
        ll = jax.device_put(llens)
        o, ol = lfn(ld, ll)
        _sync(ol)                                # compile + run
        t0 = time.perf_counter()
        o, ol = lfn(ld, ll)
        _sync(ol)
        lz4_ms = (time.perf_counter() - t0) * 1000
    except Exception:
        pass

    mb = B * blk / (1 << 20)
    # achieved-bandwidth % and MFU (r4 verdict #2): the plane-split
    # kernel's HBM traffic is 8 streaming reads of the raw bytes (one
    # per bit plane — the expansion fuses into each dot's operand
    # load); useful work is 8 int8 dots of (B,N)x(N,32). v5e-1 peaks:
    # ~819 GB/s HBM, ~394 TOPS int8.
    HBM_GB_S, INT8_TOPS = 819.0, 394.0
    traffic_gb = 8 * B * blk / 1e9
    tops = 8 * 2 * B * blk * 32 / 1e12
    dev_s = tpu_crc_ms / 1000
    bw_pct = 100.0 * (traffic_gb / dev_s) / HBM_GB_S
    mfu_pct = 100.0 * (tops / dev_s) / INT8_TOPS
    return {
        "cpu_crc_ms": round(cpu_ms, 3),
        "cpu_crc_ms_median": round(cpu_ms_median, 3),
        "tpu_crc_device_ms": round(tpu_crc_ms, 3),
        "tpu_crc_mb_s": round(mb / (tpu_crc_ms / 1000), 1),
        "cpu_crc_mb_s": round(mb / (cpu_ms / 1000), 1),
        "speedup": round(cpu_ms / tpu_crc_ms, 3),
        "crc_bw_pct_of_hbm": round(bw_pct, 1),
        "crc_mfu_pct": round(mfu_pct, 2),
        "rtt_ms": round(rtt1, 1),
        "transport_mb_s": round(transport_mb_s, 2),
        "lz4_device_ms_4x64k": round(lz4_ms, 1) if lz4_ms else None,
    }


class _FakeLatencyTicket:
    def __init__(self, values, delay_s):
        import threading
        self._ev = threading.Event()
        self._values = values
        threading.Timer(delay_s, self._ev.set).start()

    def done(self):
        return self._ev.is_set()

    def result(self, timeout=None):
        if not self._ev.wait(timeout):
            raise TimeoutError("fake ticket")
        return self._values


class _FakeLatencyProvider:
    """Models a device whose round trip costs ``lat_s`` per launch (the
    measured RTT of a real accelerator / dev tunnel) on a CPU-only
    host: the sync interface blocks for the whole round trip like r5's
    crc32c_many did; the async interface returns a ticket that resolves
    after the same latency — so the sync-vs-pipelined delta isolates
    exactly the dispatch-overlap win, with bit-exact outputs."""

    def __init__(self, lat_s: float):
        from librdkafka_tpu.ops import cpu as _c
        self.lat_s = lat_s
        self._cpu = _c.CpuCodecProvider()

    def crc32c_many(self, bufs):
        time.sleep(self.lat_s)
        return self._cpu.crc32c_many(bufs)

    def crc32c_submit(self, bufs):
        vals = np.asarray(self._cpu.crc32c_many(bufs), dtype=np.uint32)
        return _FakeLatencyTicket(vals, self.lat_s)


def _drive_pipelined(submit, jobs, depth=2):
    """Ticketed collection with at most ``depth`` launches in flight —
    the codec worker's consumption pattern."""
    from collections import deque
    pend = deque()
    outs = []
    t0 = time.perf_counter()
    for j in jobs:
        pend.append(submit(j))
        while len(pend) > depth:
            outs.append(pend.popleft().result(300))
    while pend:
        outs.append(pend.popleft().result(300))
    return time.perf_counter() - t0, outs


def pipeline_bench() -> dict:
    """bench.py --pipeline: synchronous vs pipelined dispatch of the
    CRC offload seam (ISSUE 1 acceptance).  Two legs:

      fake_latency — a provider modeling a device round trip
        (BENCH_PIPE_LAT_MS, default 2 ms) on CPU: the overlap win is
        measurable on any host, independent of the transport gate.
      engine — the real AsyncOffloadEngine over the jax backend this
        host has (device numbers when the transport gate is open; the
        CPU backend otherwise still exercises staging reuse + bulk
        readback vs the r5 per-call path).

    Both legs assert bit-exactness against the native CPU provider.
    Env knobs: BENCH_PIPE_JOBS (24), BENCH_PIPE_BATCHES (8, 64KB each),
    BENCH_PIPE_LAT_MS (2.0), BENCH_PIPE_DEPTH (2).
    """
    from librdkafka_tpu.ops import cpu as _c

    n_jobs = int(os.environ.get("BENCH_PIPE_JOBS", 24))
    batches = int(os.environ.get("BENCH_PIPE_BATCHES", 8))
    lat_ms = float(os.environ.get("BENCH_PIPE_LAT_MS", 2.0))
    depth = int(os.environ.get("BENCH_PIPE_DEPTH", 2))
    blk = 65536
    rng = np.random.default_rng(0)
    jobs = [[rng.integers(0, 256, blk, dtype=np.uint8).tobytes()
             for _ in range(batches)] for _ in range(n_jobs)]
    want = [list(_c.crc32c_many(j)) for j in jobs]

    out = {"jobs": n_jobs, "batches_per_job": batches,
           "block_bytes": blk, "depth": depth}

    # --- leg 1: fake-latency provider (overlap win, host-independent)
    fake = _FakeLatencyProvider(lat_ms / 1e3)
    t0 = time.perf_counter()
    got_sync = [fake.crc32c_many(j) for j in jobs]
    sync_s = time.perf_counter() - t0
    pipe_s, got_pipe = _drive_pipelined(fake.crc32c_submit, jobs, depth)
    assert [list(g) for g in got_sync] == want
    assert [g.tolist() for g in got_pipe] == want
    out["fake_latency"] = {
        "latency_ms": lat_ms,
        "sync_s": round(sync_s, 4),
        "pipelined_s": round(pipe_s, 4),
        "overlap_speedup": round(sync_s / max(pipe_s, 1e-9), 2),
    }

    # --- leg 2: the real engine over this host's jax backend
    try:
        from librdkafka_tpu.ops.tpu import TpuCodecProvider

        sync_prov = TpuCodecProvider(min_batches=1, warmup=False,
                                     min_transport_mb_s=0,
                                     pipeline_depth=0)
        pipe_prov = TpuCodecProvider(min_batches=1, warmup=False,
                                     min_transport_mb_s=0,
                                     pipeline_depth=depth, fanin_us=0)
        sync_prov.crc32c_many(jobs[0])          # compile + warm
        pipe_prov.crc32c_submit(jobs[0]).result(300)
        t0 = time.perf_counter()
        got_sync = [sync_prov.crc32c_many(j) for j in jobs]
        sync_s = time.perf_counter() - t0
        pipe_s, got_pipe = _drive_pipelined(pipe_prov.crc32c_submit,
                                            jobs, depth)
        assert got_sync == want
        assert [g.tolist() for g in got_pipe] == want
        import jax
        out["engine"] = {
            "backend": jax.devices()[0].platform,
            "n_devices": len(jax.devices()),
            "sync_s": round(sync_s, 4),
            "pipelined_s": round(pipe_s, 4),
            "overlap_speedup": round(sync_s / max(pipe_s, 1e-9), 2),
            "engine_stats": dict(pipe_prov._engine.stats),
            # per-stage percentiles (ISSUE 5): submit->launch wait,
            # launch->readback, reap — the decomposition the stats
            # JSON emits as codec_engine.stage_latency
            "stage_latency": pipe_prov._engine.stage_latency_snapshot(),
        }
        pipe_prov.close()
    except Exception as e:
        out["engine"] = {"error": repr(e)}
    if "--mesh" in sys.argv:
        # ISSUE 6 acceptance leg: device CRC throughput scaling across
        # per-device dispatch lanes, same artifact
        out["mesh"] = mesh_bench()
    return out


_HOST_POOL = None


def _host_pool():
    """Persistent worker pool for the fake provider's off-thread work —
    models the engine's long-lived dispatch thread (a fresh thread per
    ticket would charge ~0.1 ms of spawn latency per job to the
    pipeline, an artifact the real engine doesn't have)."""
    global _HOST_POOL
    if _HOST_POOL is None:
        from concurrent.futures import ThreadPoolExecutor
        _HOST_POOL = ThreadPoolExecutor(max_workers=8)
    return _HOST_POOL


class _HostJobTicket:
    """Runs ``fn`` on the pool — the engine's host-job dispatch (the
    native decompress releases the GIL, so this is true overlap,
    exactly what AsyncOffloadEngine.submit_compute(host=True) does)."""

    def __init__(self, fn):
        self._fut = _host_pool().submit(fn)

    def done(self):
        return self._fut.done()

    def result(self, timeout=None):
        return self._fut.result(timeout)


class _FakeFetchProvider(_FakeLatencyProvider):
    """Consumer-side fake: CRC tickets resolve after the modeled device
    RTT (like _FakeLatencyProvider); the decompress submit seam runs
    the native inflate on a worker thread, modeling the engine's
    dispatch thread inflating payloads while the 'device' executes the
    CRC launch.  The sync interface charges both costs inline, like the
    pre-ISSUE-2 broker thread did."""

    def crc32_many(self, bufs):
        time.sleep(self.lat_s)
        return self._cpu.crc32_many(bufs)

    def crc32c_submit(self, bufs):
        # the real submit only enqueues: the RTT and the checksum both
        # happen off the submitting thread ('on the device')
        def work():
            time.sleep(self.lat_s)
            return np.asarray(self._cpu.crc32c_many(bufs),
                              dtype=np.uint32)
        return _HostJobTicket(work)

    def decompress_many(self, codec, bufs, size_hints=None):
        return self._cpu.decompress_many(codec, bufs, size_hints)

    def decompress_submit(self, codec, bufs, size_hints=None):
        return _HostJobTicket(
            lambda: self._cpu.decompress_many(codec, bufs, size_hints))


def _drive_fetch_sync(provider, jobs):
    """The r5/pre-ISSUE-2 consumer codec phase: per partition, a
    blocking CRC verify then a blocking decompress."""
    outs = []
    t0 = time.perf_counter()
    for regions, codec, blobs in jobs:
        crcs = provider.crc32c_many(regions)
        outs.append((list(crcs), provider.decompress_many(codec, blobs)))
    return time.perf_counter() - t0, outs


def _drive_fetch_pipelined(provider, jobs, depth=2):
    """The broker's _PendingFetch admit/reap pattern: submit phase-B
    CRC + phase-C decompress tickets per partition, park up to
    ``depth`` entries, resolve strictly FIFO."""
    from collections import deque
    pend = deque()
    outs = []

    def _reap(block):
        while pend and (block or pend[0][0].done()):
            block = False
            ct, dt = pend.popleft()
            outs.append(([int(x) for x in ct.result(300)],
                         dt.result(300)))

    t0 = time.perf_counter()
    for regions, codec, blobs in jobs:
        while len(pend) >= depth:
            _reap(True)
        ct = provider.crc32c_submit(regions)
        dt = provider.decompress_submit(codec, blobs)
        pend.append((ct, dt))
        _reap(False)
    while pend:
        _reap(True)
    return time.perf_counter() - t0, outs


def fetch_pipeline_bench() -> dict:
    """bench.py --fetch-pipeline: synchronous vs pipelined consumer
    fetch codec phases (ISSUE 2 acceptance) — the PR 1 methodology on
    the consumer half.  Each job models one fetch-response partition:
    ``batches`` CRC regions to verify plus the same batches' compressed
    payloads to inflate.  Two legs:

      fake_latency — CRC rides a modeled device round trip
        (BENCH_PIPE_LAT_MS, default 2 ms); decompress is host-side in
        both modes.  Measures exactly the dispatch-overlap win on any
        host.
      engine — the real AsyncOffloadEngine: crc32c_submit +
        decompress_submit (host job on the dispatch thread) vs the
        synchronous provider calls, over this host's jax backend.

    Both legs assert the CRCs and decompressed payloads are
    bit-identical to the native CPU provider, and a codec sweep
    (lz4/snappy/gzip/zstd where available) asserts sync == pipelined
    per codec.  Env knobs: BENCH_FETCH_JOBS (24), BENCH_FETCH_BATCHES
    (8), BENCH_PIPE_LAT_MS (2.0), BENCH_FETCH_DEPTH (4 — the shipped
    tpu.fetch.pipeline.depth default), BENCH_PIPE_DEPTH (2, the engine
    launch depth of the real-engine leg).
    """
    from librdkafka_tpu.ops import cpu as _c

    n_jobs = int(os.environ.get("BENCH_FETCH_JOBS", 24))
    batches = int(os.environ.get("BENCH_FETCH_BATCHES", 8))
    lat_ms = float(os.environ.get("BENCH_PIPE_LAT_MS", 2.0))
    depth = int(os.environ.get("BENCH_FETCH_DEPTH", 4))
    eng_depth = int(os.environ.get("BENCH_PIPE_DEPTH", 2))
    prov_cpu = _c.CpuCodecProvider()

    def _make_jobs(codec, n, nb, size=65536):
        payloads = _payloads(n * nb, size)
        jobs = []
        for j in range(n):
            batch = payloads[j * nb:(j + 1) * nb]
            blobs = prov_cpu.compress_many(codec, batch)
            # the CRC regions of a real fetch are the batch bodies —
            # the compressed wire bytes
            jobs.append((blobs, codec, blobs))
        return jobs

    def _want(jobs):
        return [([int(x) for x in prov_cpu.crc32c_many(regions)],
                 prov_cpu.decompress_many(codec, blobs))
                for regions, codec, blobs in jobs]

    jobs = _make_jobs("lz4", n_jobs, batches)
    want = _want(jobs)
    out = {"jobs": n_jobs, "batches_per_job": batches, "depth": depth,
           "codec": "lz4"}

    # --- leg 1: fake-latency provider (overlap win, host-independent)
    fake = _FakeFetchProvider(lat_ms / 1e3)
    sync_s, got_sync = _drive_fetch_sync(fake, jobs)
    pipe_s, got_pipe = _drive_fetch_pipelined(fake, jobs, depth)
    assert [(list(c), d) for c, d in got_sync] == want
    assert got_pipe == want
    out["fake_latency"] = {
        "latency_ms": lat_ms,
        "sync_s": round(sync_s, 4),
        "pipelined_s": round(pipe_s, 4),
        "overlap_speedup": round(sync_s / max(pipe_s, 1e-9), 2),
    }

    # --- leg 2: the real engine over this host's jax backend
    try:
        from librdkafka_tpu.ops.tpu import TpuCodecProvider

        sync_prov = TpuCodecProvider(min_batches=1, warmup=False,
                                     min_transport_mb_s=0,
                                     pipeline_depth=0)
        pipe_prov = TpuCodecProvider(min_batches=1, warmup=False,
                                     min_transport_mb_s=0,
                                     pipeline_depth=eng_depth,
                                     fanin_us=0)
        sync_prov.crc32c_many(jobs[0][0])        # compile + warm
        pipe_prov.crc32c_submit(jobs[0][0]).result(300)
        sync_s, got_sync = _drive_fetch_sync(sync_prov, jobs)
        pipe_s, got_pipe = _drive_fetch_pipelined(pipe_prov, jobs, depth)
        assert [(list(c), d) for c, d in got_sync] == want
        assert got_pipe == want
        import jax
        out["engine"] = {
            "backend": jax.devices()[0].platform,
            "sync_s": round(sync_s, 4),
            "pipelined_s": round(pipe_s, 4),
            "overlap_speedup": round(sync_s / max(pipe_s, 1e-9), 2),
            "engine_stats": dict(pipe_prov._engine.stats),
            # per-stage percentiles (ISSUE 5): submit->launch wait,
            # launch->readback, reap — the decomposition the stats
            # JSON emits as codec_engine.stage_latency
            "stage_latency": pipe_prov._engine.stage_latency_snapshot(),
        }
        pipe_prov.close()
    except Exception as e:
        out["engine"] = {"error": repr(e)}

    # --- codec sweep: sync == pipelined, bit-identical per codec
    sweep = {}
    for codec in ("lz4", "snappy", "gzip", "zstd"):
        try:
            cj = _make_jobs(codec, 4, 4, size=16384)
        except Exception as e:
            hint = (" — pip install '.[zstd]'" if codec == "zstd"
                    else "")
            sweep[codec] = f"unavailable: {e.__class__.__name__}{hint}"
            continue
        cw = _want(cj)
        fake2 = _FakeFetchProvider(0.0005)
        _, s_out = _drive_fetch_sync(fake2, cj)
        _, p_out = _drive_fetch_pipelined(fake2, cj, depth)
        assert [(list(c), d) for c, d in s_out] == cw == p_out
        sweep[codec] = "bit-identical"
    out["codec_sweep"] = sweep
    return out


def _cpu_crc_fb(bufs, poly):
    from librdkafka_tpu.ops import cpu as _c
    prov = _c.CpuCodecProvider()
    return (prov.crc32c_many(bufs) if poly == "crc32c"
            else prov.crc32_many(bufs))


def _ensure_virtual_devices() -> int:
    """Mesh legs need >1 device.  Real multi-chip hosts (the
    MULTICHIP_r*.json environment) just report their count; CPU-only
    hosts get the tests' 8-virtual-device driver contract via XLA_FLAGS
    (a no-op for TPU/GPU platforms) — which only takes effect before
    jax initializes, so call this FIRST in any leg that wants a mesh.
    Returns the resulting visible device count."""
    import sys as _s
    if ("jax" not in _s.modules
            and "xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    import jax
    return len(jax.devices())


def mesh_bench() -> dict:
    """bench.py --mesh (also the mesh leg of --pipeline --mesh and the
    ``mesh`` blob of the default run): per-device dispatch-lane scaling
    of the engine's CRC path (ISSUE 6).

    For each device count (1, 2, 4, ... up to every visible chip) the
    same workload — BENCH_MESH_SUBS submissions of BENCH_MESH_ROWS
    64KB blocks — runs through a fresh engine, asserting bit-exactness
    vs the native CPU provider, and reports device CRC throughput plus
    the per-device launch/block split (the codec_engine.devices[] view).
    ``scaling_x`` is the full-mesh rate over the single-lane rate —
    meaningful only when the host has real parallel silicon
    (``host_cores`` is reported so a flat curve on a 1-core CI host is
    diagnosable, not alarming).  A writer-level msgset build cross-checks
    that full-mesh wire bytes equal the CPU provider's."""
    from librdkafka_tpu.ops import cpu as _c
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    ndev = _ensure_virtual_devices()
    rows = int(os.environ.get("BENCH_MESH_ROWS", 64))
    subs = int(os.environ.get("BENCH_MESH_SUBS", 6))
    blk = 65536
    rng = np.random.default_rng(6)
    bufs = [rng.integers(0, 256, blk, dtype=np.uint8).tobytes()
            for _ in range(rows)]
    prov = _c.CpuCodecProvider()
    want = [int(x) for x in prov.crc32c_many(bufs)]

    counts = [n for n in (1, 2, 4, 8) if n < ndev] + [ndev]
    legs, rates = {}, {}
    for nd in counts:
        eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=False,
                                 warmup=False, mesh_devices=nd,
                                 cpu_fallback=_cpu_crc_fb)
        try:
            # compile + warm outside the timed window
            assert eng.submit(bufs, "crc32c",
                              window=False).result(600).tolist() == want
            before = {r["id"]: r["blocks"]
                      for r in eng.devices_snapshot()}
            t0 = time.perf_counter()
            ts = [eng.submit(bufs, "crc32c", window=False)
                  for _ in range(subs)]
            for t in ts:
                assert t.result(600).tolist() == want, \
                    "mesh leg not bit-exact"
            dt = time.perf_counter() - t0
            rates[nd] = rows * blk * subs / dt / 1e6
            devrows = eng.devices_snapshot()
            # the acceptance gauge: every mesh device launched
            assert all(r["launches"] > 0 for r in devrows), devrows
            legs[str(nd)] = {
                "mb_s": round(rates[nd], 1),
                "launches": eng.stats["launches"],
                "sharded_launches": eng.stats["sharded_launches"],
                "per_device": [
                    {"id": r["id"], "launches": r["launches"],
                     "mb_s": round((r["blocks"] - before.get(r["id"], 0))
                                   * blk / dt / 1e6, 1)}
                    for r in devrows],
            }
        finally:
            eng.close()

    # wire bytes: a full-mesh provider build equals the CPU provider's
    from librdkafka_tpu.ops.tpu import TpuCodecProvider
    from librdkafka_tpu.protocol.msgset import MsgsetWriterV2, Record

    def build(provider, ticketed):
        w = MsgsetWriterV2(codec=None)
        w.build([Record(key=b"k%d" % i,
                        value=bufs[i % rows][:8192],
                        timestamp=1_700_000_000_000) for i in range(64)],
                1_700_000_000_000)
        region = w.assemble(None)
        crc = (int(provider.crc32c_submit([region]).result(300)[0])
               if ticketed else int(provider.crc32c_many([region])[0]))
        return w.patch_crc(crc)

    mp = TpuCodecProvider(min_batches=1, warmup=False,
                          min_transport_mb_s=0, mesh_devices=0)
    try:
        wire_ok = build(mp, True) == build(_c.CpuCodecProvider(), False)
    finally:
        mp.close()
    assert wire_ok, "full-mesh wire bytes diverged from CPU provider"

    # acceptance gauge through the REAL produce path: the stats JSON's
    # codec_engine.devices[] must show launches > 0 on every mesh
    # device (whole-to-one-lane groups spread cold lanes first)
    import json as _json

    from librdkafka_tpu import Producer
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "compression.backend": "tpu",
                  "compression.codec": "none",
                  "tpu.transport.min.mb.s": 0,
                  "tpu.launch.min.batches": 1, "tpu.governor": False,
                  "tpu.warmup": False, "tpu.mesh.devices": 0,
                  "linger.ms": 1})
    try:
        for _round in range(2 * ndev):
            for part in range(4):
                p.produce("mesh-bench", value=bufs[0][:4096],
                          partition=part)
            assert p.flush(300) == 0
        blob = _json.loads(p._rk.stats.emit_json())
        stats_devices = [{"id": d["id"], "launches": d["launches"]}
                         for d in blob["codec_engine"]["devices"]]
        assert len(stats_devices) == ndev and \
            all(d["launches"] > 0 for d in stats_devices), stats_devices
    finally:
        p.close()

    return {
        "n_devices": ndev,
        "host_cores": os.cpu_count(),
        "rows_per_submission": rows,
        "submissions": subs,
        "device_counts": counts,
        "crc_mb_s": {str(nd): round(r, 1) for nd, r in rates.items()},
        "scaling_x": round(rates[counts[-1]] / max(rates[1], 1e-9), 2),
        "wire_bitexact": True,
        "stats_devices": stats_devices,
        "legs": legs,
    }


def governor_bench() -> dict:
    """bench.py --governor (ISSUE 3 acceptance): the adaptive offload
    governor measured leg by leg, every leg asserting bit-exactness vs
    the native CPU provider.

      cold_start — first-submission latency through the engine with
        background warmup (the warmup gate serves from CPU instantly;
        the compile happens off the hot path) vs without warmup (the
        first launch stalls submit->result behind the inline XLA
        compile).  Acceptance: warm first-launch <= 10% of the
        no-warmup cold start on at least one bucket shape.  Also
        reports the first DEVICE launch after the bucket warms.
      fanin — adaptive vs static fan-in window at a low submission
        rate (per-ticket latency: adaptive must shed the window tax)
        and a high rate (burst wall-clock: adaptive must not be
        slower).
      fused — mixed crc32c + legacy-crc32 submissions merge into ONE
        launch with per-row polynomial selection.

    Env knobs: BENCH_GOV_BLOCKS (12, 64KB each), BENCH_GOV_FANIN_N
    (24 tickets/leg).
    """
    import jax  # noqa: F401  (pay the import before any timed leg)

    from librdkafka_tpu.ops import cpu as _c
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine
    from librdkafka_tpu.utils.crc import crc32, crc32c

    prov = _c.CpuCodecProvider()
    rng = np.random.default_rng(0)
    blk = 65536
    nblk = int(os.environ.get("BENCH_GOV_BLOCKS", 12))
    out = {}

    # --- leg 1: cold start ----------------------------------------------
    bufs = [rng.integers(0, 256, blk, dtype=np.uint8).tobytes()
            for _ in range(nblk)]
    want = prov.crc32c_many(bufs)
    want32 = prov.crc32_many(bufs)

    # no warmup: the first submission stalls behind the inline compile
    # (crc32 poly so the warm leg's crc32c bucket stays cold for it)
    cold_eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=False,
                                  warmup=False, cpu_fallback=None)
    t0 = time.perf_counter()
    got = cold_eng.submit(bufs, "crc32", window=False).result(600)
    cold_s = time.perf_counter() - t0
    assert got.tolist() == want32, "cold leg not bit-exact"
    cold_eng.close()

    # warmup: the same first-submission shape is served instantly from
    # the CPU provider while the kernel compiles in the background
    warm_eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=True,
                                  warmup=True, cpu_fallback=_cpu_crc_fb)
    t0 = time.perf_counter()
    got = warm_eng.submit(bufs, "crc32c", window=False).result(600)
    warm_first_s = time.perf_counter() - t0
    assert got.tolist() == want, "warm leg not bit-exact"
    # ... and once the bucket compiles, the device route opens
    bucket = 64 if nblk <= 64 else (128 if nblk <= 128 else 256)
    opened = warm_eng.warm_wait(bucket, "crc32c", 600)
    dev_first_s = None
    if opened:
        launches = warm_eng.stats["launches"]
        t0 = time.perf_counter()
        got = warm_eng.submit(bufs, "crc32c", window=False).result(600)
        dev_first_s = time.perf_counter() - t0
        assert got.tolist() == want, "device leg not bit-exact"
        assert warm_eng.stats["launches"] == launches + 1, \
            "warmed bucket did not ride a device launch"
    warm_stats = dict(warm_eng.stats)
    warm_eng.close()
    ratio = warm_first_s / max(cold_s, 1e-9)
    out["cold_start"] = {
        "blocks": nblk,
        "no_warmup_first_launch_s": round(cold_s, 4),
        "warmup_first_launch_s": round(warm_first_s, 4),
        "warmup_over_cold_ratio": round(ratio, 4),
        "within_10pct": ratio <= 0.10,
        "first_device_launch_s": (round(dev_first_s, 4)
                                  if dev_first_s is not None else None),
        "engine_stats": warm_stats,
    }

    # --- leg 2: adaptive vs static fan-in ---------------------------------
    n = int(os.environ.get("BENCH_GOV_FANIN_N", 24))
    small = [rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
             for _ in range(2)]
    want_small = prov.crc32c_many(small)

    def _lat_leg(adaptive: bool, ia_s: float):
        eng = AsyncOffloadEngine(depth=2, fanin_window_s=0.0005,
                                 min_batches=8, governor=adaptive,
                                 warmup=False, cpu_fallback=_cpu_crc_fb)
        lats = []
        for _ in range(n):
            t0 = time.perf_counter()
            t = eng.submit(small, "crc32c", window=True)
            got = t.result(60)
            lats.append(time.perf_counter() - t0)
            assert got.tolist() == want_small, "fanin leg not bit-exact"
            if ia_s:
                time.sleep(ia_s)
        st = dict(eng.stats)
        eng.close()
        lats = sorted(lats[4:])          # drop the model warm-in
        return lats[len(lats) // 2], st

    def _burst_leg(adaptive: bool):
        eng = AsyncOffloadEngine(depth=2, fanin_window_s=0.0005,
                                 min_batches=8, governor=adaptive,
                                 warmup=False, cpu_fallback=_cpu_crc_fb)
        t0 = time.perf_counter()
        tickets = [eng.submit(small, "crc32c", window=True)
                   for _ in range(n)]
        for t in tickets:
            assert t.result(60).tolist() == want_small, \
                "burst leg not bit-exact"
        wall = time.perf_counter() - t0
        eng.close()
        return wall

    static_p50, static_st = _lat_leg(False, 0.004)
    adapt_p50, adapt_st = _lat_leg(True, 0.004)
    static_burst = _burst_leg(False)
    adapt_burst = _burst_leg(True)
    out["fanin"] = {
        "tickets_per_leg": n,
        "low_rate_4ms": {
            "static_p50_us": round(static_p50 * 1e6, 1),
            "adaptive_p50_us": round(adapt_p50 * 1e6, 1),
            "latency_shed": round(static_p50 / max(adapt_p50, 1e-9), 2),
            "adaptive_fanin_skips": adapt_st["fanin_skips"],
            "static_fanin_waits": static_st["fanin_waits"],
        },
        "high_rate_burst": {
            "static_wall_s": round(static_burst, 4),
            "adaptive_wall_s": round(adapt_burst, 4),
            "adaptive_not_slower":
                adapt_burst <= static_burst * 1.25,
        },
    }

    # --- leg 3: fused multi-poly launches ---------------------------------
    eng = AsyncOffloadEngine(depth=2, fanin_window_s=0.05, min_batches=4,
                             governor=True, warmup=False,
                             cpu_fallback=_cpu_crc_fb)
    m1 = [rng.integers(0, 256, 8192, dtype=np.uint8).tobytes()
          for _ in range(2)]
    m2 = [rng.integers(0, 256, 8192, dtype=np.uint8).tobytes()
          for _ in range(2)]
    t1 = eng.submit(m1, "crc32c", window=True)
    t2 = eng.submit(m2, "crc32", window=True)
    assert t1.result(300).tolist() == [crc32c(b) for b in m1], \
        "fused crc32c rows not bit-exact"
    assert t2.result(300).tolist() == [crc32(b) for b in m2], \
        "fused crc32 rows not bit-exact"
    out["fused"] = {
        "launches": eng.stats["launches"],
        "fused_launches": eng.stats["fused_launches"],
        "halved": eng.stats["fused_launches"] >= 1
        and eng.stats["launches"] == 1,
        "governor": eng.governor_snapshot(),
    }
    eng.close()
    return out


def codec_device_bench(smoke: bool = False) -> dict:
    """bench.py --codec-device (ISSUE 17): the device compress route
    measured leg by leg, every leg asserting frames bit-identical to
    the deterministic CPU encoder (the device kernel's spec).

      buckets — per-bucket fused compress→CRC launch rate vs the
        native deterministic encoder on the same buffers.  On this
        1-core CPU-jax host the device loses (that is WHY the governor
        routes compress to CPU here and tpu.compress.device defaults
        false); the leg exists to keep both sides measured and
        bit-exact so real accelerators can flip the default.
      warm_gate — first-submission latency with background warmup
        (CPU-served instantly, compile off the hot path) vs without
        (inline XLA compile stall).  Acceptance: warm first submission
        <= 10% of the cold stall; once warm, the same shape rides a
        device launch.
      headline — e2e 1KB-lz4 producer msgs/s, forced device route vs
        host compress jobs, same external mock broker.

    Env knobs: BENCH_DC_MSGS (e2e messages; 3000 smoke / 20000 full).
    """
    import jax  # noqa: F401  (pay the import before any timed leg)

    from librdkafka_tpu.ops import cpu as _c
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    def _det(bufs):
        return _c.lz4f_compress_many(list(bufs), deterministic=True)

    rng = np.random.default_rng(17)
    out = {}

    # --- leg 1: per-bucket device vs CPU rate -----------------------------
    rounds = 2 if smoke else 6
    buckets = {}
    for nblk in (4,) if smoke else (4, 16):
        # semi-compressible 32KB bodies: one LZ4F block per buffer
        bufs = [bytes(rng.integers(0, 16, 32768, dtype=np.uint8))
                for _ in range(nblk)]
        nbytes = sum(len(b) for b in bufs)
        want = _det(bufs)
        eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=False,
                                 warmup=False, cpu_fallback=_cpu_crc_fb,
                                 cpu_compress_fallback=_det)
        # compile outside the timed window
        assert [bytes(f) for f in eng.submit_compress(
            bufs, window=False).result(600)] == want, \
            "device bucket leg not bit-exact"
        t0 = time.perf_counter()
        for _ in range(rounds):
            assert [bytes(f) for f in eng.submit_compress(
                bufs, window=False).result(600)] == want
        dev_s = (time.perf_counter() - t0) / rounds
        snap = eng.compress_snapshot()
        eng.close()
        t0 = time.perf_counter()
        for _ in range(rounds):
            assert _det(bufs) == want
        cpu_s = (time.perf_counter() - t0) / rounds
        bucket = snap["routed"] and sorted(snap["routed"])[0]
        buckets[str(bucket)] = {
            "blocks": nblk,
            "device_mb_s": round(nbytes / dev_s / 1e6, 1),
            "cpu_mb_s": round(nbytes / cpu_s / 1e6, 1),
            "device_over_cpu": round(cpu_s / max(dev_s, 1e-9), 4),
            "fused_crc_launches": snap["fused_crc"],
            "bit_exact": True,
        }
        assert snap["launches"] >= rounds + 1, snap
        assert snap["fused_crc"] >= rounds + 1, snap
    out["buckets"] = buckets

    # --- leg 2: warm gate vs inline-compile cold start --------------------
    wb = [bytes(rng.integers(0, 16, 8192, dtype=np.uint8))
          for _ in range(4)]                      # 4 blocks -> bucket 8
    want_w = _det(wb)
    cold_eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=False,
                                  warmup=False, cpu_fallback=_cpu_crc_fb,
                                  cpu_compress_fallback=_det)
    t0 = time.perf_counter()
    assert [bytes(f) for f in cold_eng.submit_compress(
        wb, window=False).result(600)] == want_w
    cold_s = time.perf_counter() - t0
    cold_eng.close()                  # releases the compiled kernels

    warm_eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=False,
                                  warmup=True, cpu_fallback=_cpu_crc_fb,
                                  cpu_compress_fallback=_det)
    t0 = time.perf_counter()
    assert [bytes(f) for f in warm_eng.submit_compress(
        wb, window=False).result(600)] == want_w
    warm_first_s = time.perf_counter() - t0
    dev_first_s = None
    if warm_eng.lz4_warm_wait(8, 8192, 600):
        launches = warm_eng.compress_stats["launches"]
        t0 = time.perf_counter()
        assert [bytes(f) for f in warm_eng.submit_compress(
            wb, window=False).result(600)] == want_w
        dev_first_s = time.perf_counter() - t0
        assert warm_eng.compress_stats["launches"] == launches + 1, \
            "warmed lz4 bucket did not ride a device launch"
    warm_eng.close()
    ratio = warm_first_s / max(cold_s, 1e-9)
    out["warm_gate"] = {
        "no_warmup_first_submit_s": round(cold_s, 4),
        "warmup_first_submit_s": round(warm_first_s, 4),
        "warmup_over_cold_ratio": round(ratio, 4),
        "within_10pct": ratio <= 0.10,
        "first_device_launch_s": (round(dev_first_s, 4)
                                  if dev_first_s is not None else None),
    }

    # --- leg 3: e2e 1KB-lz4 headline --------------------------------------
    n = int(os.environ.get("BENCH_DC_MSGS", 3000 if smoke else 20000))
    base = {"tpu.transport.min.mb.s": 0, "tpu.governor": False,
            "tpu.warmup": False, "tpu.launch.min.batches": 1}
    dev_rate = host_pipeline(n, 1024, 4, backend="tpu",
                             extra_conf={**base,
                                         "tpu.compress.device": True})
    host_rate = host_pipeline(n, 1024, 4, backend="tpu",
                              extra_conf=base)
    out["headline_1kb_lz4"] = {
        "msgs": n,
        "device_route_msgs_s": round(dev_rate),
        "host_route_msgs_s": round(host_rate),
        "device_over_host": round(dev_rate / max(host_rate, 1e-9), 4),
    }
    return out


def chaos_bench() -> dict:
    """bench.py --chaos (<60 s): the chaos smoke leg — run every FAST
    scenario from the chaos library (broker kill/restart, a real
    SIGKILL+SIGSTOP storm against the out-of-process cluster, group
    churn, network shaping, the oracle self-test) and gate on a clean
    delivery-invariant verdict; the full storms live behind
    scripts/chaos.sh (pytest -m chaos; --soak adds the soak tier).

    Robustness-as-numbers (ISSUE 9): the external storm's throughput
    under fire (``storm_msgs_s``) and post-SIGKILL recovery latency
    (``recovery_*_ms`` time-to-first-ack) surface at top level so the
    BENCH_r* trajectory tracks robustness regressions, not just
    speed."""
    from librdkafka_tpu.chaos.oracle import OracleViolation
    from librdkafka_tpu.chaos.scenarios import SCENARIOS

    legs = {}
    all_ok = True
    for name, sc in SCENARIOS.items():
        if sc.tier != "fast":
            continue
        t0 = time.perf_counter()
        try:
            report = sc.fn()
            # the self-tests PASS by detecting their planted violation
            # and proving the dump artifacts exist
            ok = ((not report["ok"] and bool(report.get("diff_path"))
                   and bool(report.get("flight_path")))
                  if name in ("oracle_selftest",
                              "oracle_continuity_selftest") else
                  (report["ok"] and not report["errors"]
                   and not report["schedule_errors"]))
            legs[name] = {
                "ok": ok, "acked": report.get("acked"),
                "consumed": report.get("consumed"),
                "violations": {k: len(v) for k, v in
                               report["violations"].items() if v},
                "wall_s": round(time.perf_counter() - t0, 2)}
            if report.get("storm_metrics"):
                legs[name]["storm_metrics"] = report["storm_metrics"]
            if report.get("group"):
                legs[name]["group"] = {
                    k: report["group"][k]
                    for k in ("members", "live", "departed",
                              "assignments", "converged_s")}
        except (OracleViolation, Exception) as e:  # noqa: B014
            legs[name] = {"ok": False, "error": repr(e),
                          "wall_s": round(time.perf_counter() - t0, 2)}
        all_ok = all_ok and legs[name]["ok"]
    ext = (legs.get("fast_external_kill9") or {}).get("storm_metrics") or {}
    rec = ext.get("recovery_ms") or {}
    return {"ok": all_ok,
            "storm_msgs_s": ext.get("storm_msgs_s"),
            "storm_kills": ext.get("kills"),
            "recovery_p50_ms": rec.get("p50"),
            "recovery_p99_ms": rec.get("p99"),
            "recovery_max_ms": rec.get("max"),
            "legs": legs}


def rebalance_bench(smoke: bool = False) -> dict:
    """bench.py --rebalance (ISSUE 12): eager vs KIP-429 cooperative
    rebalancing for a 50-member group (12 in ``--smoke``) under
    join/leave churn on the thread-cheap member harness — no broker
    faults, pure protocol comparison.  Per leg: convergence time after
    the last membership change, TOTAL partition-unavailability seconds
    (integrated zero-active-fetcher time — eager's stop-the-world
    cost), and messages flowing DURING rebalance windows.  The
    headline ``coop_unavail_ratio`` (cooperative / eager
    unavailability) must hold ≤ 0.2 for the 50-member leg."""
    from librdkafka_tpu.chaos.scenarios import LiteStorm
    from librdkafka_tpu.chaos.schedule import Schedule

    members = 12 if smoke else 50
    churners = 2 if smoke else 5
    duration = 4.0 if smoke else 6.0
    legs = {}
    for strategy in ("range", "cooperative-sticky"):
        t0 = time.perf_counter()
        storm = LiteStorm(
            seed=71, brokers=1, partitions=64, external=False,
            members=members, churners=churners,
            churn_start_s=1.8, churn_period_s=0.4,
            churn_lifetime_s=1.6, strategy=strategy, threads=6,
            heartbeat_s=0.4, member_stagger_s=0.01,
            duration_s=duration, pace_ms=2, drain_s=25.0,
            converge_s=30.0, check_continuity=True, flow_stall_s=3.0,
            # KIP-134 initial hold: the fleet joins ONE first
            # generation (otherwise member 0 grabs all partitions and
            # both protocols pay an immediate mass redistribution)
            initial_delay_ms=700)
        try:
            report = storm.run(Schedule(seed=71),
                               raise_on_violation=False)
        except Exception as e:  # noqa: B014 — leg must report, not die
            legs[strategy] = {"ok": False, "error": repr(e)}
            continue
        intervals = storm.fleet.rebalancing_intervals()
        with storm.oracle._lock:
            stamps = [t for ts in storm.oracle.flow.values()
                      for t in ts]
        msgs_during = sum(1 for t in stamps
                          if any(a <= t <= b for a, b in intervals))
        reb_s = round(sum(b - a for a, b in intervals), 2)
        # continuity violations only apply to the cooperative contract
        bad = {k: len(v) for k, v in report["violations"].items()
               if v and (strategy != "range" or k != "flow_gap")}
        legs[strategy] = {
            "ok": not bad and not report["errors"],
            "violations": bad,
            "members": members + churners,
            "acked": report["acked"], "consumed": report["consumed"],
            "converged_s": report["converged_s"],
            "unavailability_s":
                report["partition_unavailability"]["total_s"],
            "rebalancing_s": reb_s,
            "msgs_during_rebalance": msgs_during,
            "msgs_per_rebalance_s":
                round(msgs_during / reb_s, 1) if reb_s else None,
            "incremental": strategy != "range",
            "wall_s": round(time.perf_counter() - t0, 2)}
    eager = legs.get("range", {})
    coop = legs.get("cooperative-sticky", {})
    ratio = None
    if eager.get("unavailability_s") and \
            coop.get("unavailability_s") is not None:
        ratio = round(coop["unavailability_s"]
                      / eager["unavailability_s"], 3)
    return {
        "ok": all(leg.get("ok") for leg in legs.values()) and bool(legs),
        "group_members": members + churners,
        "eager_unavailability_s": eager.get("unavailability_s"),
        "coop_unavailability_s": coop.get("unavailability_s"),
        "coop_unavail_ratio": ratio,
        "eager_converged_s": eager.get("converged_s"),
        "coop_converged_s": coop.get("converged_s"),
        "eager_msgs_during_rebalance":
            eager.get("msgs_during_rebalance"),
        "coop_msgs_during_rebalance": coop.get("msgs_during_rebalance"),
        "legs": legs,
    }


def fleet_bench(smoke: bool = False) -> dict:
    """bench.py --fleet: the multi-process fleet leg (ISSUE 11).

    Full mode runs the FLAGSHIP fleet storm — ≥24 real client OS
    processes under diurnal+burst traffic with hot-key/hot-partition
    skew against the supervised 3-broker cluster, sustaining 3
    pid-verified SIGKILLs, an asymmetric brownout and an EIO window —
    and surfaces the fleet aggregate at artifact top level:
    ``fleet_msgs_s``, per-client produce->ack p99 (max + median),
    ``storm_kills``, and post-kill ``recovery_p50/p99_ms``.

    ``--fleet --smoke`` runs the 2-worker mini fleet instead (<20 s):
    same machinery — spawn, stream-merge, per-group verify — at the
    smallest honest scale, the pre-commit shape."""
    from librdkafka_tpu.chaos.oracle import OracleViolation
    from librdkafka_tpu.fleet.scenarios import fleet_mini, fleet_storm

    t0 = time.perf_counter()
    try:
        report = fleet_mini() if smoke else fleet_storm()
        ok = (report["ok"] and not report["errors"]
              and not report["schedule_errors"])
    except (OracleViolation, Exception) as e:  # noqa: B014
        return {"ok": False, "error": repr(e),
                "wall_s": round(time.perf_counter() - t0, 2)}
    fm = report.get("fleet_metrics") or {}
    sm = report.get("storm_metrics") or {}
    rec = sm.get("recovery_ms") or {}
    return {
        "ok": ok,
        "leg": "fleet_mini" if smoke else "fleet_storm",
        "workers": report.get("workers"),
        "fleet_msgs_s": fm.get("fleet_msgs_s"),
        "client_p99_ms_max": fm.get("client_p99_ms_max"),
        "client_p99_ms_median": fm.get("client_p99_ms_median"),
        "client_p99_ms": fm.get("client_p99_ms"),
        "storm_kills": sm.get("kills", 0),
        "recovery_p50_ms": rec.get("p50"),
        "recovery_p99_ms": rec.get("p99"),
        "acked": report.get("acked"),
        "consumed_by_group": report.get("consumed_by_group"),
        "converged_s": report.get("converged_s"),
        "replay_key": report.get("replay_key"),
        "wall_s": round(time.perf_counter() - t0, 2),
    }


def _session_wire_leg(n_parts: int, enable: bool, produce_parts: int,
                      n_msgs: int, steady_s: float):
    """One fetch-session wire leg: a consumer assigned to ALL
    ``n_parts`` partitions (the interest set) with data on the first
    ``produce_parts``; returns (delivered records, steady-state
    Fetch-API wire bytes over ``steady_s``, session stats)."""
    from librdkafka_tpu import Consumer, Producer
    from librdkafka_tpu.client.consumer import TopicPartition
    from librdkafka_tpu.mock.cluster import MockCluster

    cluster = MockCluster(num_brokers=1, topics={"wt": n_parts})
    try:
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "linger.ms": 2})
        for i in range(n_msgs):
            p.produce("wt", value=b"w%06d" % i,
                      partition=i % produce_parts)
        assert p.flush(60.0) == 0
        p.close()

        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "bw", "auto.offset.reset": "earliest",
                      "fetch.session.enable": enable})
        c.assign([TopicPartition("wt", i) for i in range(n_parts)])
        records = []
        deadline = time.monotonic() + 120
        while len(records) < n_msgs and time.monotonic() < deadline:
            m = c.poll(0.2)
            if m is not None and m.error is None:
                records.append((m.partition, m.offset, m.value))
        assert len(records) == n_msgs, \
            f"delivery incomplete: {len(records)}/{n_msgs}"
        # warm-up barrier: offset resolution is one ListOffsets round
        # trip per partition, so a 10k assign keeps turning partitions
        # ACTIVE (and folding them into the session book) for seconds
        # after delivery completes — measure steady state only once the
        # whole interest set is fetchable on both legs
        from librdkafka_tpu.client.partition import FetchState
        rk = c._rk
        warm_deadline = time.monotonic() + 180
        warmed = False
        while time.monotonic() < warm_deadline:
            c.poll(0.1)
            tps = list(rk.active_toppars())
            if (len(tps) < n_parts or any(
                    tp.fetch_state != FetchState.ACTIVE for tp in tps)):
                continue
            if not enable:
                warmed = True
                break
            with rk._brokers_lock:
                bs = list(rk.brokers.values())
            if sum(b._fetch_session.stats()["partitions_total"]
                   for b in bs) >= n_parts:
                warmed = True
                break
        assert warmed, "interest set never fully fetchable"
        # steady state: everything consumed, only long-polls remain —
        # the window where incremental sessions collapse the wire
        with rk._brokers_lock:
            data_brokers = [b for b in rk.brokers.values()]
        tx0 = sum(b.c_fetch_tx_bytes for b in data_brokers)
        rx0 = sum(b.c_fetch_rx_bytes for b in data_brokers)
        t_end = time.monotonic() + steady_s
        while time.monotonic() < t_end:
            c.poll(0.1)
        wire = (sum(b.c_fetch_tx_bytes for b in data_brokers) - tx0
                + sum(b.c_fetch_rx_bytes for b in data_brokers) - rx0)
        sess = [b._fetch_session.stats() for b in data_brokers
                if b._fetch_session.stats()["partitions_total"]
                or not enable]
        c.close()
        return records, wire, sess
    finally:
        cluster.stop()


def partitions_bench(smoke: bool = False) -> dict:
    """bench.py --partitions (ISSUE 14): many-partition scale.

    Two sweeps against the in-process mock:

    * scale legs — a topic with 1k / 10k / 100k partitions (1k only in
      ``--smoke``): first-produce time (metadata registration of the
      whole partition table), paced produce msgs/s to 8 partitions,
      and stats-emit wall time.  The emitter is O(active), so
      ``stats_emit_ms`` must stay flat while registered toppars grow
      100x.

    * wire legs — sessionless vs KIP-227 incremental fetch sessions
      with the SAME 10k-partition interest set (1k in ``--smoke``):
      delivered records must be bit-identical, and the steady-state
      Fetch wire bytes must drop >= 10x (the headline
      ``wire_reduction``)."""
    from librdkafka_tpu import Producer
    from librdkafka_tpu.client.errors import KafkaException
    from librdkafka_tpu.mock.cluster import MockCluster

    t_start = time.perf_counter()
    counts = [1000] if smoke else [1000, 10000, 100000]
    scale = {}
    for n in counts:
        cluster = MockCluster(num_brokers=1, topics={"pt": n})
        try:
            p = Producer({"bootstrap.servers":
                          cluster.bootstrap_servers(), "linger.ms": 2})
            t0 = time.perf_counter()
            p.produce("pt", value=b"warm", partition=0)
            assert p.flush(120.0) == 0
            md_s = time.perf_counter() - t0
            n_msgs = 2000 if smoke else 20000
            t0 = time.perf_counter()
            for i in range(n_msgs):
                while True:
                    try:
                        p.produce("pt", value=b"v%06d" % i,
                                  partition=i % 8)
                        break
                    except KafkaException as e:
                        if e.error.code.name != "_QUEUE_FULL":
                            raise
                        p.poll(0.01)
                p.poll(0)
            assert p.flush(120.0) == 0
            msgs_s = n_msgs / (time.perf_counter() - t0)
            emits = []
            for _ in range(5):
                t0 = time.perf_counter()
                p._rk.stats.emit_json()
                emits.append(time.perf_counter() - t0)
            p.close()
            scale[str(n)] = {
                "first_produce_s": round(md_s, 3),
                "produce_msgs_s": int(msgs_s),
                "stats_emit_ms": round(min(emits) * 1e3, 3)}
        finally:
            cluster.stop()
    # stats-emit flatness across a 10-100x registered-toppar spread
    emit_ms = [leg["stats_emit_ms"] for leg in scale.values()]
    emit_flat = max(emit_ms) / max(min(emit_ms), 1e-3)

    wire_parts = 1000 if smoke else 10000
    produce_parts = 64 if smoke else 256
    wire_msgs = 1000 if smoke else 4000
    steady_s = 1.5 if smoke else 3.0
    rec_off, wire_off, _ = _session_wire_leg(
        wire_parts, False, produce_parts, wire_msgs, steady_s)
    rec_on, wire_on, sess = _session_wire_leg(
        wire_parts, True, produce_parts, wire_msgs, steady_s)
    bit_identical = sorted(rec_off) == sorted(rec_on)
    reduction = round(wire_off / max(wire_on, 1), 1)
    return {
        "ok": bool(bit_identical and reduction >= 10.0
                   and emit_flat < 10.0),
        "scale": scale,
        "stats_emit_flatness": round(emit_flat, 2),
        "wire_interest_set": wire_parts,
        "wire_bytes_sessionless": wire_off,
        "wire_bytes_session": wire_on,
        "wire_reduction": reduction,
        "delivered_bit_identical": bit_identical,
        "fetch_sessions": sess,
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }


def _fastlane_smoke_leg() -> dict:
    """Small-message fast-lane gate (ISSUE 16).  Three assertions:

    (a) wire-byte equality slow-vs-fast: every headers x timestamp x
        codec combo, routed per-partition exactly as native murmur2
        auto-partition routes it, frames bit-identically through the
        fused native builder vs the pure-Python writer + provider
        codec/CRC slow path;
    (b) engagement ratio: an eligible small-message shape (100B keyed,
        murmur2 auto-partition, explicit ts + headers, dr_msg_cb set)
        rides the native lane for >=99% of appends with ZERO
        demotions;
    (c) stage latency: the traced leg decomposes into the
        run_take/native_frame spans, percentiles reported in the
        --json artifact.
    """
    import tempfile

    from librdkafka_tpu import Producer
    from librdkafka_tpu.client.arena import _mod, encode_headers
    from librdkafka_tpu.ops.cpu import CpuCodecProvider
    from librdkafka_tpu.protocol.msgset import MsgsetWriterV2, Record
    from librdkafka_tpu.utils.hash import murmur2_partition

    m = _mod()
    assert m is not None and hasattr(m, "build_batch"), \
        "fast-lane gate needs the native tk_enqlane module"
    prov = CpuCodecProvider()
    now_ms = 1722900000123

    def run_from(recs):
        parts, klens, vlens, tss, hbufs, hlens = [], [], [], [], [], []
        for k, v, ts, hdrs in recs:
            klens.append(-1 if k is None else len(k))
            vlens.append(-1 if v is None else len(v))
            if k is not None:
                parts.append(k)
            if v is not None:
                parts.append(v)
            tss.append(ts)
            hb = encode_headers(hdrs) if hdrs else b""
            hbufs.append(hb)
            hlens.append(len(hb))
        return (b"".join(parts),
                np.array(klens, np.int32).tobytes(),
                np.array(vlens, np.int32).tobytes(),
                np.array(tss, np.int64).tobytes() if any(tss) else None,
                b"".join(hbufs) if any(hlens) else None,
                np.array(hlens, np.int32).tobytes() if any(hlens)
                else None)

    # (a) wire equality across the widened-eligibility matrix
    combos = 0
    for with_hdrs in (False, True):
        for with_ts in (False, True):
            for codec in ("none", "lz4", "snappy"):
                recs = []
                for i in range(32):
                    recs.append((b"key-%02d" % i, b"v%02d" % i * 25,
                                 now_ms + i * 13 if with_ts else 0,
                                 ([("h", b"%d" % i), ("n", None)]
                                  if with_hdrs else ())))
                # auto-partition: route through murmur2 exactly as the
                # native lane would, then gate EVERY partition's run
                groups = {}
                for r in recs:
                    groups.setdefault(
                        murmur2_partition(r[0], 4), []).append(r)
                for grp in groups.values():
                    msgs = [Record(key=k, value=v,
                                   timestamp=ts if ts else -1,
                                   headers=h)
                            for k, v, ts, h in grp]
                    w = MsgsetWriterV2(
                        codec=None if codec == "none" else codec)
                    w._build_py(msgs, now_ms)
                    comp = None
                    if codec != "none":
                        c = prov.compress_many(codec,
                                               [w.records_bytes])[0]
                        if len(c) < len(w.records_bytes):
                            comp = c
                        else:
                            w.codec = None
                    slow = w.patch_crc(int(prov.crc32c_many(
                        [w.assemble(comp)])[0]))
                    base, kl, vl, tsb, hb, hlb = run_from(grp)
                    fast = m.build_batch(
                        base, kl, vl, len(grp), now_ms, -1, -1, -1,
                        {"none": 0, "snappy": 2, "lz4": 3}[codec], 0,
                        tsb, hb, hlb)
                    assert bytes(fast) == slow, (
                        f"fast-lane wire mismatch: hdrs={with_hdrs} "
                        f"ts={with_ts} codec={codec}")
                    combos += 1

    # (b)+(c): eligible shape engagement + per-stage trace percentiles
    drs = [0]

    def _dr(err, msg):
        assert err is None
        drs[0] += 1

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "trace.enable": True, "linger.ms": 5,
                  "queue.buffering.max.messages": 200_000,
                  "dr_msg_cb": _dr})
    p.set_topic_conf("fastlane", {"partitioner": "murmur2"})
    trace_path = os.path.join(tempfile.gettempdir(),
                              f"tk_fastlane_trace_{os.getpid()}.json")
    n_msgs = 20_000
    try:
        # murmur2 auto-partition needs the partition count: wait for
        # the metadata round trip before the timed produce loop
        p.rk.get_topic("fastlane")
        deadline = time.monotonic() + 30
        while (p.rk.topics["fastlane"].partition_cnt <= 0
               and time.monotonic() < deadline):
            p.poll(0.05)
        assert p.rk.topics["fastlane"].partition_cnt > 0
        hdrs = [("src", b"smoke")]
        val = b"x" * 100
        for i in range(n_msgs):
            p.produce("fastlane", value=val, key=b"k%05d" % (i % 512),
                      timestamp=now_ms + i, headers=hdrs)
            if i % 4096 == 0:
                p.poll(0)
        assert p.flush(120.0) == 0
        assert drs[0] == n_msgs, f"DRs {drs[0]}/{n_msgs}"
        ctrs = p.rk._lane.counters()
        total = ctrs["engaged"] + sum(ctrs["fallback"].values())
        ratio = ctrs["engaged"] / total if total else 0.0
        assert ratio >= 0.99, f"fast-lane engagement {ratio:.4f} < 0.99"
        assert p.rk._demote_reasons == {}, p.rk._demote_reasons
        n_ev = p.trace_dump(trace_path)
        summary = _traceview().summarize(
            _traceview().load_events(trace_path))
        stages = {s["name"]: s for s in summary["stages"]}
        assert "run_take" in stages, \
            f"fast-lane trace missing run_take: {sorted(stages)}"
        # the frame stage is "fused_build" on the one-call native path
        # (frame+compress+CRC fused) and "native_frame" on the writer
        # path (non-native codec / device-routed provider)
        frame = next((n for n in ("fused_build", "native_frame")
                      if n in stages), None)
        assert frame, f"fast-lane trace missing frame span: " \
                      f"{sorted(stages)}"
        stage_lat = {n: {k: stages[n][k]
                         for k in ("cnt", "p50_us", "p90_us", "p99_us",
                                   "max_us")}
                     for n in ("run_take", frame)}
    finally:
        p.close()
        try:
            os.unlink(trace_path)
        except OSError:
            pass
    return {"wire_combos": combos,
            "engaged": ctrs["engaged"],
            "engagement_ratio": round(ratio, 5),
            "trace_events": n_ev,
            "stage_latency": stage_lat}


def smoke_bench() -> dict:
    """bench.py --smoke (<60 s): one bit-exactness pass over every
    engine leg — sync provider, pipelined engine, fetch pipeline,
    governor (warmup-gate routing + fused multi-poly) — the pre-commit
    gate next to scripts/tier1.sh."""
    # first: mesh legs need >1 device, and the virtual-device contract
    # only applies before jax initializes
    n_devices = _ensure_virtual_devices()

    from librdkafka_tpu.ops import cpu as _c
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine
    from librdkafka_tpu.ops.tpu import TpuCodecProvider
    from librdkafka_tpu.utils.crc import crc32, crc32c

    t_start = time.perf_counter()
    prov = _c.CpuCodecProvider()
    rng = np.random.default_rng(0)
    bufs = [b"", b"123456789",
            rng.integers(0, 256, 4096, dtype=np.uint8).tobytes(),
            rng.integers(0, 256, 70000, dtype=np.uint8).tobytes()]
    want_c = prov.crc32c_many(bufs)
    want_l = prov.crc32_many(bufs)
    legs = {}

    # sync provider route
    sp = TpuCodecProvider(min_batches=1, warmup=False,
                          min_transport_mb_s=0, pipeline_depth=0)
    assert sp.crc32c_many(bufs) == want_c, "sync leg not bit-exact"
    legs["sync"] = "bit-identical"

    # pipelined engine route (ticketed, both polynomials)
    pp = TpuCodecProvider(min_batches=1, warmup=False,
                          min_transport_mb_s=0, pipeline_depth=2,
                          fanin_us=0)
    assert pp.crc32c_submit(bufs).result(120).tolist() == want_c, \
        "pipelined leg not bit-exact"
    pp.close()
    legs["pipelined"] = "bit-identical"

    # consumer fetch pipeline (ticketed phases B+C, sync == pipelined)
    jobs = []
    for j in range(3):
        batch = _payloads(4, 8192)
        blobs = prov.compress_many("lz4", batch)
        jobs.append((blobs, "lz4", blobs))
    want_fetch = [([int(x) for x in prov.crc32c_many(r)],
                   prov.decompress_many(c, b)) for r, c, b in jobs]
    fake = _FakeFetchProvider(0.0005)
    _, s_out = _drive_fetch_sync(fake, jobs)
    _, p_out = _drive_fetch_pipelined(fake, jobs, 4)
    assert [(list(c), d) for c, d in s_out] == want_fetch == p_out, \
        "fetch pipeline leg not bit-exact"
    legs["fetch_pipeline"] = "bit-identical"

    # governor: warmup-gate routing (CPU-served pre-warm, device after)
    eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=True,
                             warmup=True, cpu_fallback=_cpu_crc_fb)
    assert eng.submit(bufs, "crc32c",
                      window=False).result(60).tolist() == want_c, \
        "governor pre-warm leg not bit-exact"
    opened = eng.warm_wait(64, "crc32c", 30)
    if opened:
        assert eng.submit(bufs, "crc32c",
                          window=False).result(60).tolist() == want_c, \
            "governor device leg not bit-exact"
    legs["governor"] = ("bit-identical (device opened)" if opened
                        else "bit-identical (CPU-routed; warmup still "
                             "compiling)")
    eng.close()

    # fused multi-poly (inline compile — small shapes)
    eng2 = AsyncOffloadEngine(depth=2, fanin_window_s=0.05, min_batches=4,
                              governor=True, warmup=False,
                              cpu_fallback=_cpu_crc_fb)
    m = [rng.integers(0, 256, 2048, dtype=np.uint8).tobytes()
         for _ in range(2)]
    t1 = eng2.submit(m, "crc32c", window=True)
    t2 = eng2.submit(m, "crc32", window=True)
    assert t1.result(120).tolist() == [crc32c(b) for b in m]
    assert t2.result(120).tolist() == [crc32(b) for b in m]
    fused = eng2.stats["fused_launches"]
    eng2.close()
    legs["fused"] = f"bit-identical ({fused} fused launch)"

    # device compress route (ISSUE 17): the fused compress→CRC launch
    # must hand back LZ4F frames byte-identical to the deterministic
    # CPU encoder, with the per-part CRCs folding to the true crc32c
    from librdkafka_tpu.ops.packing import FrameBlob
    from librdkafka_tpu.utils.crc import crc32c as _crc32c
    dc = TpuCodecProvider(min_batches=1, warmup=False,
                          min_transport_mb_s=0, compress_device=True)
    cbufs = [b"", b"smoke-dc",
             bytes(rng.integers(0, 16, 4096, dtype=np.uint8)),
             rng.integers(0, 256, 3000, dtype=np.uint8).tobytes()]
    want_fr = _c.lz4f_compress_many(cbufs, deterministic=True)
    got_fr = dc.compress_submit(
        "lz4", cbufs, qos=[("smoke", 1.0)] * len(cbufs)).result(300)
    assert [bytes(f) for f in got_fr] == want_fr, \
        "device compress leg not bit-exact"
    blobs = [f for f in got_fr if isinstance(f, FrameBlob)]
    assert blobs and all(f.region_crc() == _crc32c(bytes(f))
                         for f in blobs), "fused CRC parts wrong"
    dsnap = dc._engine.compress_snapshot()
    assert dsnap["launches"] >= 1 and dsnap["fused_crc"] >= 1, dsnap
    dc.close()
    legs["device_codec"] = (f"bit-identical ({dsnap['fused_crc']} fused "
                            f"compress→CRC launch)")

    # mesh dispatch lanes (ISSUE 6): 2-device bit-exactness — one
    # group big enough to shard across both chips, plus small groups
    # spreading whole-to-one-lane — auto-skipped when <2 devices
    if n_devices >= 2:
        eng3 = AsyncOffloadEngine(depth=2, min_batches=1,
                                  governor=False, warmup=False,
                                  mesh_devices=2,
                                  cpu_fallback=_cpu_crc_fb)
        big = [rng.integers(0, 256, 65536, dtype=np.uint8).tobytes()
               for _ in range(16)]
        assert eng3.submit(big, "crc32c",
                           window=False).result(300).tolist() == \
            [crc32c(b) for b in big], "mesh sharded leg not bit-exact"
        assert eng3.stats["sharded_launches"] >= 1, eng3.stats
        for _ in range(3):
            assert eng3.submit(bufs, "crc32c",
                               window=False).result(120).tolist() == \
                want_c, "mesh lane leg not bit-exact"
        rows = eng3.devices_snapshot()
        # scaling sanity: both lanes exist and both launched
        assert len(rows) == 2 and all(r["launches"] > 0 for r in rows), \
            rows
        eng3.close()
        legs["mesh"] = ("bit-identical (sharded across 2 devices; "
                        "both lanes launched)")
    else:
        legs["mesh"] = f"skipped ({n_devices} device)"

    # transactional producer round trip (ISSUE 4): commit then abort
    # through the real Producer API against the in-process mock — the
    # log must end data..COMMIT..data..ABORT with an aborted-txn index
    # entry covering only the aborted range
    from librdkafka_tpu import Producer
    from librdkafka_tpu.protocol.msgset import read_batch_header
    from librdkafka_tpu.utils.buf import Slice
    tp_ = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                    "transactional.id": "smoke-tx",
                    "compression.codec": "lz4", "linger.ms": 1})
    try:
        tp_.init_transactions(30)
        tp_.begin_transaction()
        for i in range(5):
            tp_.produce("smoke-txn", value=b"c%d" % i, partition=0)
        tp_.commit_transaction(30)
        tp_.begin_transaction()
        for i in range(5):
            tp_.produce("smoke-txn", value=b"a%d" % i, partition=0)
        tp_.flush(30)
        tp_.abort_transaction(30)
        part = tp_._rk.mock_cluster.partition("smoke-txn", 0)
        infos = [read_batch_header(Slice(bytes(b))) for _o, b in part.log]
        assert [i.is_control for i in infos] == [False, True, False, True], \
            "txn leg: log is not data,COMMIT,data,ABORT"
        assert all(i.is_transactional for i in infos), \
            "txn leg: batch missing the transactional attr bit"
        assert len(part.aborted) == 1, "txn leg: aborted-txn index wrong"
        legs["txn"] = "commit+abort markers + aborted index correct"
    finally:
        tp_.close()

    # traced e2e leg (ISSUE 5): a produce+consume round trip with
    # trace.enable=true must decompose into the pipeline stages in a
    # dump that scripts/traceview.py can summarize
    import tempfile

    from librdkafka_tpu import Consumer
    from librdkafka_tpu.obs import trace as _tr

    tp2 = Producer({"bootstrap.servers": "",
                    "test.mock.num.brokers": 1, "trace.enable": True,
                    "compression.backend": "tpu",
                    "tpu.transport.min.mb.s": 0,
                    "tpu.launch.min.batches": 2, "tpu.governor": False,
                    "tpu.warmup": False, "compression.codec": "lz4",
                    "linger.ms": 10})
    tc2 = None
    trace_path = os.path.join(tempfile.gettempdir(),
                              f"tk_smoke_trace_{os.getpid()}.json")
    try:
        bs2 = tp2._rk.mock_cluster.bootstrap_servers()
        tp2.produce("smoke-trace", value=b"solo", partition=0)
        assert tp2.flush(120.0) == 0
        for i in range(200):
            tp2.produce("smoke-trace", value=b"v%d" % i * 20,
                        partition=i % 4)
        assert tp2.flush(120.0) == 0
        tc2 = Consumer({"bootstrap.servers": bs2, "group.id": "smoke-tr",
                        "auto.offset.reset": "earliest",
                        "check.crcs": True, "trace.enable": True})
        tc2.subscribe(["smoke-trace"])
        got = 0
        deadline = time.monotonic() + 60
        while got < 201 and time.monotonic() < deadline:
            m = tc2.poll(0.2)
            if m is not None and m.error is None:
                got += 1
        assert got == 201, f"traced consume incomplete: {got}/201"
        n_events = tp2.trace_dump(trace_path)
        summary = _traceview().summarize(
            _traceview().load_events(trace_path))
        stages = {s["name"] for s in summary["stages"]}
        need = {"compress", "crc_ticket", "fanin_wait", "device_launch",
                "readback", "crc_verify", "decompress", "deliver",
                "produce_tx", "ack", "batch_assembly"}
        missing = need - stages
        assert not missing, f"traced leg missing stages: {missing}"
        legs["trace"] = (f"{n_events} events, "
                         f"{len(stages)} stages, all expected present")
    finally:
        tp2.close()
        if tc2 is not None:
            tc2.close()
        try:
            os.unlink(trace_path)
        except OSError:
            pass

    # incremental fetch sessions (ISSUE 14): session-on vs session-off
    # over the same 64-partition interest set must deliver the exact
    # same (partition, offset, value) set
    rec_off, wire_off, _ = _session_wire_leg(64, False, 8, 200, 0.5)
    rec_on, wire_on, fs = _session_wire_leg(64, True, 8, 200, 0.5)
    assert sorted(rec_off) == sorted(rec_on), \
        "fetch-session leg not bit-exact"
    assert fs and fs[0]["epoch"] >= 1, fs
    legs["fetch_session"] = (f"bit-identical (steady wire "
                             f"{wire_off}B sessionless -> {wire_on}B "
                             f"incremental)")

    # small-message fast lane (ISSUE 16): wire equality across the
    # widened-eligibility matrix + >=99% engagement + stage latency
    fl = _fastlane_smoke_leg()
    _fr = next(n for n in fl["stage_latency"] if n != "run_take")
    legs["fast_lane"] = (f"bit-identical ({fl['wire_combos']} "
                         f"partition-runs), engagement "
                         f"{fl['engagement_ratio']:.2%}, {_fr} p50 "
                         f"{fl['stage_latency'][_fr]['p50_us']}us")

    trace_ovh = _trace_overhead_gate()
    return {"elapsed_s": round(time.perf_counter() - t_start, 1),
            "legs": legs,
            "fast_lane": fl,
            "trace_overhead": trace_ovh,
            "lockdep_overhead": _lockdep_overhead_gate(
                trace_ovh["produce_ns_per_msg"]),
            "races_overhead": _races_overhead_gate(
                trace_ovh["produce_ns_per_msg"])}


def _traceview():
    """scripts/traceview.py as a module (scripts/ is not a package)."""
    import importlib.util
    p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "scripts", "traceview.py")
    spec = importlib.util.spec_from_file_location("tk_traceview", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _trace_overhead_gate() -> dict:
    """Disabled-observability overhead gate (ISSUE 5 satellite,
    extended by ISSUE 20 to the whole obs plane): the ONLY cost a
    hooks-absent build removes is the per-site ``if trace.enabled:`` /
    ``if metrics.enabled:`` attribute check, so the gate measures each
    guard directly and scales it by a conservative hook count per
    message, against the measured per-message cost of a real produce
    leg.  trace + metrics disabled must be within 2% of hooks-absent
    COMBINED."""
    import timeit

    from librdkafka_tpu import Producer
    from librdkafka_tpu.obs import metrics as _mx
    from librdkafka_tpu.obs import trace as _tr

    assert not _tr.enabled
    assert not _mx.enabled
    n, reps = 200_000, 5
    # the guard alone: timeit of the attribute load minus the empty
    # loop (the loop machinery is shared by both builds, so only the
    # delta is a cost a hooks-absent build would shed).  min-of-repeats
    # rather than one long sample: a scheduler preemption inside a
    # single timeit window inflates the reading 2x on a loaded CI host,
    # while the minimum estimates the actual instruction cost
    loaded = min(timeit.repeat("t.enabled", globals={"t": _tr},
                               repeat=reps, number=n))
    mloaded = min(timeit.repeat("m.enabled", globals={"m": _mx},
                                repeat=reps, number=n))
    empty = min(timeit.repeat("pass", repeat=reps, number=n))
    guard_ns = max(0.0, (loaded - empty) / n * 1e9)
    metrics_guard_ns = max(0.0, (mloaded - empty) / n * 1e9)
    # per-message budget: a quick produce leg over the in-process mock
    # (GIL-shared, so this UNDERSTATES the budget — conservative)
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "linger.ms": 5, "compression.codec": "lz4",
                  "queue.buffering.max.messages": 500_000})
    try:
        val = b"x" * 100
        for i in range(2000):           # warm sockets + codecs
            p.produce("ovh", value=val, partition=i % 4)
        assert p.flush(60.0) == 0
        n_msgs = 30_000
        t0 = time.perf_counter()
        for i in range(n_msgs):
            p.produce("ovh", value=val, partition=i % 4)
        assert p.flush(60.0) == 0
        msg_ns = (time.perf_counter() - t0) / n_msgs * 1e9
    finally:
        p.close()
    # the per-MESSAGE hook count is exactly 1 (the produce-enqueue
    # site; fast-lane records run zero Python hooks); the ~10
    # per-BATCH span sites (assembly, compress, crc, tx, ack, engine
    # fanin/launch/readback) amortize below 0.1/message at this leg's
    # batch sizes (hundreds of messages per linger window) — bound the
    # amortized share at 0.25, a >2x margin
    hooks_per_msg = 1.25
    # metrics-registry sites fire per batch / per stats row, never per
    # message (engine launch, fleet ack rows, chaos steps) — bound the
    # amortized per-message share at 0.5, a wide margin over reality
    metrics_hooks_per_msg = 0.5
    overhead_pct = guard_ns * hooks_per_msg / msg_ns * 100.0
    combined_pct = ((guard_ns * hooks_per_msg
                     + metrics_guard_ns * metrics_hooks_per_msg)
                    / msg_ns * 100.0)
    return {"guard_ns": round(guard_ns, 2),
            "metrics_guard_ns": round(metrics_guard_ns, 2),
            "produce_ns_per_msg": round(msg_ns, 1),
            "hooks_per_msg_bound": hooks_per_msg,
            "metrics_hooks_per_msg_bound": metrics_hooks_per_msg,
            "overhead_pct": round(overhead_pct, 4),
            "combined_overhead_pct": round(combined_pct, 4),
            "acceptance_pct_lt": 2.0,
            "pass": bool(combined_pct < 2.0)}


def _lockdep_overhead_gate(produce_ns_per_msg: float) -> dict:
    """Disabled-lockdep overhead gate (ISSUE 8 satellite, same
    methodology as the PR 5 trace gate): with the checker off, the
    analysis.locks factory hands back PLAIN threading primitives — the
    plain-vs-instrumented decision is made once at lock CREATION, so
    the only conceivable per-message cost is a factory-made lock being
    slower than a raw one.  The gate measures both round trips
    directly and scales the delta by a conservative bound on lock
    round trips per produced message (msg_cnt claim + toppar/arena
    enqueue + broker queue push + DR accounting), against the measured
    produce budget from the trace gate's leg.  Must stay < 1%."""
    import threading
    import timeit

    from librdkafka_tpu.analysis import lockdep as _ld
    from librdkafka_tpu.analysis.locks import new_lock

    assert not _ld.enabled
    factory = new_lock("bench.lockdep_gate")
    plain = threading.Lock()
    assert type(factory) is type(plain), \
        "disabled factory must return a plain threading.Lock"
    n = 200_000
    t_factory = min(timeit.repeat(
        "l.acquire(); l.release()", globals={"l": factory},
        number=n, repeat=5))
    t_plain = min(timeit.repeat(
        "l.acquire(); l.release()", globals={"l": plain},
        number=n, repeat=5))
    delta_ns = max(0.0, (t_factory - t_plain) / n * 1e9)
    locks_per_msg = 4.0
    overhead_pct = delta_ns * locks_per_msg / produce_ns_per_msg * 100.0
    return {"factory_lock_ns": round(t_factory / n * 1e9, 2),
            "plain_lock_ns": round(t_plain / n * 1e9, 2),
            "delta_ns": round(delta_ns, 2),
            "locks_per_msg_bound": locks_per_msg,
            "produce_ns_per_msg": round(produce_ns_per_msg, 1),
            "overhead_pct": round(overhead_pct, 4),
            "acceptance_pct_lt": 1.0,
            "pass": bool(overhead_pct < 1.0)}


def _races_overhead_gate(produce_ns_per_msg: float) -> dict:
    """Disabled-lockset overhead gate (ISSUE 10 satellite, same
    methodology as the lockdep gate): with the detector off, a
    ``shared()`` class-body marker DELETES itself at class creation —
    the attribute is a plain instance attribute, so the only
    conceivable per-message cost is that attribute being slower than
    one on an undeclared class (it cannot be: the class dicts are
    identical after removal, which the gate asserts).  Measures the
    declared-vs-plain read-modify-write round trip directly and scales
    the delta by a conservative bound on declared-field accesses per
    produced message.  Must stay < 1%."""
    import timeit

    from librdkafka_tpu.analysis import races as _rc

    assert not _rc.enabled

    class _Declared:
        x = _rc.shared("bench.races_gate")

        def __init__(self):
            self.x = 0

    class _Plain:
        def __init__(self):
            self.x = 0

    assert "x" not in _Declared.__dict__, \
        "disabled shared() marker must resolve to a plain attribute"
    n = 200_000
    t_decl = min(timeit.repeat(
        "o.x = o.x + 1", globals={"o": _Declared()}, number=n, repeat=5))
    t_plain = min(timeit.repeat(
        "o.x = o.x + 1", globals={"o": _Plain()}, number=n, repeat=5))
    delta_ns = max(0.0, (t_decl - t_plain) / n * 1e9)
    # declared-field touches per produced message: toppar queue
    # accounting (msgq/msgq_bytes enqueue+drain) dominates; counters
    # and engine fields amortize per batch — bound at 8
    accesses_per_msg = 8.0
    overhead_pct = (delta_ns * accesses_per_msg
                    / produce_ns_per_msg * 100.0)
    return {"declared_rmw_ns": round(t_decl / n * 1e9, 2),
            "plain_rmw_ns": round(t_plain / n * 1e9, 2),
            "delta_ns": round(delta_ns, 2),
            "accesses_per_msg_bound": accesses_per_msg,
            "produce_ns_per_msg": round(produce_ns_per_msg, 1),
            "overhead_pct": round(overhead_pct, 4),
            "acceptance_pct_lt": 1.0,
            "pass": bool(overhead_pct < 1.0)}


def main():
    if "--mesh" in sys.argv:
        # must run before ANY leg initializes jax, so CPU hosts get
        # the 8-virtual-device contract for the mesh measurements
        _ensure_virtual_devices()
    if "--mesh" in sys.argv and "--pipeline" not in sys.argv:
        _emit({"metric": "mesh-sharded codec engine: per-device "
                                    "dispatch-lane CRC scaling "
                                    "(bench.py --mesh)",
                          **mesh_bench()})
        return
    if "--chaos" in sys.argv:
        _emit({"metric": "chaos smoke: fast fault-schedule storms "
                         "with a clean delivery-invariant oracle "
                         "verdict (bench.py --chaos)",
               **chaos_bench()})
        return
    if "--rebalance" in sys.argv:
        _emit({"metric": "eager vs cooperative incremental rebalance: "
                         "convergence time, partition-unavailability "
                         "seconds, messages flowing mid-rebalance for "
                         "a 50-member group (bench.py --rebalance)",
               **rebalance_bench(smoke="--smoke" in sys.argv)})
        return
    if "--fleet" in sys.argv:
        _emit({"metric": "multi-process client fleet: aggregate "
                         "msgs/s, per-client p99, recovery envelopes "
                         "under SIGKILL+brownout+EIO (bench.py "
                         "--fleet)",
               **fleet_bench(smoke="--smoke" in sys.argv)})
        return
    if "--governor" in sys.argv:
        _emit({"metric": "adaptive offload governor: warmup "
                                    "cold-start, adaptive fan-in, fused "
                                    "multi-poly launches (bench.py "
                                    "--governor)",
                          **governor_bench()})
        return
    if "--codec-device" in sys.argv:
        _emit({"metric": "device-side batch compression: fused "
                         "compress→CRC launch rate per bucket, "
                         "warm-gate cold start, e2e 1KB-lz4 headline "
                         "(bench.py --codec-device)",
               **codec_device_bench(smoke="--smoke" in sys.argv)})
        return
    if "--txn" in sys.argv:
        _emit({"metric": "transactional vs plain idempotent "
                                    "produce throughput (bench.py "
                                    "--txn)",
                          **txn_bench()})
        return
    if "--partitions" in sys.argv:
        _emit({"metric": "many-partition scale: O(active) stats emit "
                         "+ incremental fetch-session wire reduction "
                         "at 1k-100k toppars (bench.py --partitions)",
               **partitions_bench(smoke="--smoke" in sys.argv)})
        return
    if "--smoke" in sys.argv:
        _emit({"metric": "pre-commit smoke: bit-exactness "
                                    "over every engine leg (bench.py "
                                    "--smoke)",
                          **smoke_bench()})
        return
    if "--fetch-pipeline" in sys.argv:
        _emit({"metric": "pipelined vs synchronous consumer "
                                    "fetch codec phases (bench.py "
                                    "--fetch-pipeline)",
                          **fetch_pipeline_bench()})
        return
    if "--pipeline" in sys.argv:
        _emit({"metric": "pipelined vs synchronous codec "
                                    "offload dispatch (bench.py "
                                    "--pipeline)",
                          **pipeline_bench()})
        return
    # ~1s of steady state per trial: short runs understate the rate by
    # folding the constant linger+flush tail into it (measured 119k
    # @40k msgs vs 171k @240k, same config). The round-4 pipeline runs
    # ~500k msgs/s, so the default trial is 500k messages now.
    n_msgs = int(os.environ.get("BENCH_MSGS", 500000))
    size = int(os.environ.get("BENCH_MSG_SIZE", 1024))
    toppars = int(os.environ.get("BENCH_TOPPARS", 16))
    # median of 3 per backend, INTERLEAVED cpu/tpu pairs: the shared
    # host's load drifts minute-to-minute, and running the two backends
    # in separate phases let that drift masquerade as a backend
    # difference (observed both directions across driver runs).
    # backend=tpu must be >= cpu e2e: lz4 routes to the native CPU path
    # (tpu.lz4.force off) and the adaptive transport gate keeps CRC on
    # CPU when host<->device bandwidth can't pay for the launch.
    # consumer FIRST: it runs before anything imports jax, so the
    # recorded number isn't taxed by the jax/axon runtime's background
    # threads on this 1-core host (measured 167k in-process-with-jax vs
    # ~250k without; the producer cpu-vs-tpu comparison below stays
    # interleaved so that tax hits both sides of ITS comparison)
    consumer_rate = None
    consumer_small_rate = None
    try:
        # 5 trials, median: trial 0 pays the VM pager's first-touch
        # cost for the working set (~21 us/page on this infra); the
        # steady state is what transfers
        rates = [consumer_pipeline(n_msgs, size, toppars)
                 for _ in range(5)]
        consumer_rate = sorted(rates)[2]
        # the reference's >3M msgs/s consumer headline shape: small
        # uncompressed messages (README.md:12) — median of 3
        _reset_mock()
        srates = [consumer_pipeline(min(n_msgs, 400_000), 100, 8,
                                    codec="none") for _ in range(3)]
        consumer_small_rate = sorted(srates)[1]
    except Exception as e:
        # null in the JSON must be diagnosable, never silent
        print(f"consumer_pipeline failed: {e!r}", file=sys.stderr)
    finally:
        # a failed trial must not leak a wrong-partition-count mock
        # into the next block
        _reset_mock()
    producer_small_rate = None
    try:
        # the reference's >1M msgs/s producer headline shape
        # (README.md:11): small uncompressed messages — median of 3
        prates = [host_pipeline(min(n_msgs, 400_000), 100, 8,
                                extra_conf={"compression.codec": "none"})
                  for _ in range(3)]
        producer_small_rate = sorted(prates)[1]
    except Exception as e:
        print(f"producer small failed: {e!r}", file=sys.stderr)
    finally:
        _reset_mock()
    cpu_rates, tpu_rates = [], []
    try:
        for _ in range(3):
            cpu_rates.append(host_pipeline(n_msgs, size, toppars))
            tpu_rates.append(host_pipeline(n_msgs, size, toppars,
                                           backend="tpu"))
    except BaseException:
        if _MOCK_PROC is not None:
            _MOCK_PROC.kill()
        raise
    host_rate = sorted(cpu_rates)[1]
    tpu_backend_rate = sorted(tpu_rates)[1]
    # delivery-report modes (the reference's headline runs WITH DRs):
    # per-message dr_msg_cb and the batched dr_batch_cb (one call per
    # delivered batch, the rd_kafka_event_DR message-array idea)
    dr_rate = dr_batch_rate = None
    try:
        _cnt = [0]

        def _dr_msg(err, m):
            _cnt[0] += 1

        def _dr_batch(msgs):
            _cnt[0] += len(msgs)

        dr_rate = host_pipeline(n_msgs, size, toppars,
                                extra_conf={"dr_msg_cb": _dr_msg})
        dr_batch_rate = host_pipeline(
            n_msgs, size, toppars, extra_conf={"dr_batch_cb": _dr_batch})
    except Exception as e:
        print(f"dr pipeline failed: {e!r}", file=sys.stderr)
    # BASELINE config 5: 64-toppar idempotent producer (fresh mock with
    # 64 partitions; PID FSM + per-batch sequence numbering in play)
    idem_rate = None
    try:
        _reset_mock()
        idem_rate = host_pipeline(
            n_msgs, size, 64,
            extra_conf={"enable.idempotence": True})
    except Exception as e:
        print(f"idempotent_64tp failed: {e!r}", file=sys.stderr)
    finally:
        _reset_mock()
    sweep = None
    if os.environ.get("BENCH_SWEEP", "1") != "0":
        try:
            sweep = codec_size_sweep(toppars)
        except Exception as e:
            print(f"codec_size_sweep failed: {e!r}", file=sys.stderr)
        finally:
            _reset_mock()
    off = codec_offload()
    # mesh dispatch-lane scaling (ISSUE 6): recorded in the BENCH_r*
    # trajectory whenever this host has >1 device (the multichip
    # environment); 1-device hosts skip — a 1-lane "curve" is noise
    mesh = None
    if os.environ.get("BENCH_MESH", "1") != "0":
        try:
            import jax
            if len(jax.devices()) >= 2:
                mesh = mesh_bench()
            else:
                mesh = {"skipped": "1 device visible",
                        "n_devices": 1}
        except Exception as e:
            print(f"mesh_bench failed: {e!r}", file=sys.stderr)
    _emit({
        "metric": "batched CRC32C codec offload, 128x64KB partition "
                  "batches (64 toppars x 2 blocks): TPU plane-split MXU "
                  "kernel device rate, bit-exact vs the native CPU "
                  "provider (vs_baseline = idle-host CPU time / device "
                  "time; see PERF.md — the dev tunnel is 2-3 MB/s so "
                  "e2e offload measures transport, not kernels)",
        "value": off["tpu_crc_mb_s"],
        "unit": "MB/s",
        "vs_baseline": off["speedup"],
        "host_pipeline_msgs_s": round(host_rate, 1),
        "host_pipeline_tpu_backend_msgs_s": round(tpu_backend_rate, 1),
        "consumer_pipeline_msgs_s":
            round(consumer_rate, 1) if consumer_rate is not None else None,
        "consumer_small_100b_msgs_s":
            round(consumer_small_rate, 1)
            if consumer_small_rate is not None else None,
        "producer_small_100b_msgs_s":
            round(producer_small_rate, 1)
            if producer_small_rate is not None else None,
        "idempotent_64tp_msgs_s":
            round(idem_rate, 1) if idem_rate is not None else None,
        "producer_dr_msgs_s":
            round(dr_rate, 1) if dr_rate is not None else None,
        "producer_dr_batch_msgs_s":
            round(dr_batch_rate, 1) if dr_batch_rate is not None else None,
        "codec_size_sweep": sweep,
        "mesh": mesh,
        "detail": off,
    })


if __name__ == "__main__":
    sys.exit(main())
