#!/usr/bin/env python
"""Driver benchmark — the BASELINE.json headline config: producer msgs/sec
at 1KB messages with lz4 compression (rdkafka_performance -P equivalent,
reference examples/rdkafka_performance.c:555-644), full client pipeline
against the in-process mock cluster.

Prints ONE JSON line:
  {"metric": ..., "value": <tpu msgs/sec>, "unit": "msgs/s",
   "vs_baseline": <tpu_rate / cpu_rate>}

vs_baseline is the speedup of the compression.backend=tpu pipeline over
the same pipeline with the CPU codec provider (the reference-architecture
path: per-batch sequential compress+CRC on the broker thread).
Env knobs: BENCH_MSGS (default 40000), BENCH_MSG_SIZE (1024),
BENCH_TOPPARS (16 partitions — the batch-offload axis).
"""
import json
import os
import sys
import time


def _payloads(n: int, size: int) -> list[bytes]:
    # semi-compressible 1KB payloads (json-ish), like real event streams
    out = []
    base = (b'{"seq": %07d, "user": "u%05d", "event": "click", '
            b'"props": "abcdefghijklmnopqrstuvwxyz0123456789"}')
    for i in range(n):
        b = base % (i, i % 1000)
        out.append((b * (size // len(b) + 1))[:size])
    return out


def run(backend: str, n_msgs: int, size: int, toppars: int) -> float:
    from librdkafka_tpu import Producer

    p = Producer({
        "bootstrap.servers": "", "test.mock.num.brokers": 1,
        "test.mock.default.partitions": toppars,
        "compression.backend": backend,
        "compression.codec": "lz4",
        "batch.num.messages": 10000,
        "linger.ms": 50,
        "queue.buffering.max.messages": 2_000_000,
        "tpu.launch.min.batches": 2,
    })
    vals = _payloads(n_msgs, size)
    # warmup: trigger jit compiles for the padded sizes + socket path
    for i in range(2000):
        p.produce("bench", value=vals[i % len(vals)], partition=i % toppars)
    if p.flush(600.0) != 0:
        raise RuntimeError("warmup flush did not drain")

    t0 = time.perf_counter()
    for i, v in enumerate(vals):
        p.produce("bench", value=v, partition=i % toppars)
    if p.flush(600.0) != 0:
        raise RuntimeError("bench flush did not drain")
    dt = time.perf_counter() - t0
    p.close()
    return n_msgs / dt


def main():
    n_msgs = int(os.environ.get("BENCH_MSGS", 40000))
    size = int(os.environ.get("BENCH_MSG_SIZE", 1024))
    toppars = int(os.environ.get("BENCH_TOPPARS", 16))
    cpu_rate = run("cpu", n_msgs, size, toppars)
    tpu_rate = run("tpu", n_msgs, size, toppars)
    print(json.dumps({
        "metric": "producer throughput, 1KB msgs, lz4, %d toppars "
                  "(tpu codec offload vs cpu provider)" % toppars,
        "value": round(tpu_rate, 1),
        "unit": "msgs/s",
        "vs_baseline": round(tpu_rate / cpu_rate, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
